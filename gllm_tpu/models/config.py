"""Model configuration, parsed from HF config.json dicts.

The reference threads serving decisions through the HF config object
(/root/reference/gllm/model_loader.py:188-334 propagate_*). We instead parse
into one frozen dataclass that the functional model code closes over — every
field is static at trace time, which is what jit wants.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    architecture: str
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    rope_scaling: Optional[Dict[str, Any]] = None
    max_position: int = 8192
    tie_word_embeddings: bool = False
    attention_bias: bool = False      # qwen2-style qkv bias
    qk_norm: bool = False             # qwen3-style per-head q/k RMSNorm
    partial_rotary_factor: float = 1.0  # GLM: rotate only this prefix of D
    rope_interleaved: bool = False    # GLM/DeepSeek pair-interleaved layout
    sandwich_norms: bool = False      # GLM4 post_self_attn/post_mlp norms
    # int, tuple of ints, or None. Checkpoints like GLM4 / Llama-3 declare
    # several terminators (reference llm_engine.py finish_tokens treats
    # eos_token_id as a list); use ``eos_token_ids`` for finish checks.
    eos_token_id: Any = None
    bos_token_id: Optional[int] = None

    @property
    def eos_token_ids(self) -> Tuple[int, ...]:
        v = self.eos_token_id
        if v is None:
            return ()
        if isinstance(v, (list, tuple)):
            return tuple(v)
        return (v,)
    hidden_act: str = "silu"
    # MoE fields (0 experts → dense). See gllm_tpu/models/moe.py.
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    num_shared_experts: int = 0
    norm_topk_prob: bool = True
    # Set by the DP runner: route MoE through the dense masked path (the
    # ragged grouped GEMM doesn't batch under vmap).
    moe_force_dense: bool = False
    # Set by the runner when cache.kv_cache_dtype == "int8": the paged
    # KV cache stores int8 payload + per-page per-head f32 scales
    # (dense.init_kv_cache / ops/kv_cache.write_kv_quant). Spec builders
    # (parallel/shardings.kv_cache_specs) read it so the spec pytree
    # mirrors the cache's scale leaves.
    kv_cache_quant: bool = False
    decoder_sparse_step: int = 1      # every Nth layer is MoE (qwen2-moe)
    mlp_only_layers: Tuple[int, ...] = ()
    shared_expert_intermediate_size: int = 0

    # MLA (DeepSeek V2/V3 — reference models/deepseek_v2.py)
    q_lora_rank: int = 0              # 0 → direct q projection (V2-Lite)
    kv_lora_rank: int = 0             # > 0 enables MLA latent cache
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # DeepSeek MoE routing
    first_k_dense_replace: int = 0
    n_shared_experts: int = 0
    routed_scaling_factor: float = 1.0
    n_group: int = 0
    topk_group: int = 0
    scoring_func: str = "softmax"     # softmax (V2) | sigmoid (V3)
    topk_method: str = "greedy"       # greedy | group_limited_greedy |
                                      # noaux_tc (V3 bias-corrected)

    @property
    def use_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def mla_cache_width(self) -> int:
        """Latent-row width PADDED to the 128-lane Mosaic tile so the
        Pallas kernels can DMA pages (576 → 640 for DeepSeek V3; the pad
        lanes stay zero and q is zero-padded to match, so scores are
        unchanged on every path)."""
        width = self.kv_lora_rank + self.qk_rope_head_dim
        return width + (-width) % 128

    # DeepSeek V3.2 sparse attention (DSA — reference deepseek_v32.py):
    # lightning indexer scoring + top-k physical-slot selection.
    index_n_heads: int = 0
    index_head_dim: int = 0
    index_topk: int = 0

    @property
    def use_dsa(self) -> bool:
        return self.index_topk > 0 and self.index_n_heads > 0

    # Multimodal (Qwen-VL family — reference models/qwen2_5_vl.py,
    # rotary_embedding.py:607-706). mrope_section sums to rot_dim/2;
    # vision_config is the raw HF vision sub-config dict, parsed by
    # gllm_tpu/models/vision.py.
    mrope_section: Tuple[int, ...] = ()
    # Qwen3-VL: frequency-interleaved [THTHW...] mrope layout instead of
    # chunked [T|H|W] sections (HF apply_interleaved_mrope).
    mrope_interleaved: bool = False
    image_token_id: int = -1
    video_token_id: int = -1
    vision_config: Optional[Dict[str, Any]] = None
    # Qwen3-VL deepstack: the ViT emits (1 + n) stacked features per visual
    # token; level i is added to the LM hidden stream after layer i
    # (reference qwen3_vl.py:436-469 Qwen3LLMModel deepstack injection).
    deepstack_num_levels: int = 0
    # Qwen3-VL videos: each temporal frame is its own vision span with a
    # timestamp text run between frames; grids are normalized to t=1
    # per-frame items (HF get_rope_index splits video_grid_thw the same way).
    mm_per_frame_video: bool = False

    @property
    def use_mm(self) -> bool:
        return self.vision_config is not None

    @property
    def mm_embed_dim(self) -> int:
        """Width of one spliced visual row ([main ‖ deepstack levels])."""
        return self.hidden_size * (1 + self.deepstack_num_levels)

    # Hybrid linear-attention (Qwen3-Next / Qwen3.5 — reference
    # models/qwen3_5.py). layer_types marks each layer "linear_attention"
    # or "full_attention".
    layer_types: Tuple[str, ...] = ()
    linear_num_value_heads: int = 0
    linear_num_key_heads: int = 0
    linear_key_head_dim: int = 0
    linear_value_head_dim: int = 0
    linear_conv_kernel_dim: int = 4

    @property
    def use_hybrid(self) -> bool:
        return "linear_attention" in self.layer_types

    @property
    def stage_layer_types(self) -> Tuple[str, ...]:
        """layer_types restricted to this PP stage's layer range."""
        a, b = self.stage_layers
        return self.layer_types[a:b]

    @property
    def num_attn_layers(self) -> int:
        """Full-attention layers OWNED BY THIS STAGE (= the whole model
        when un-staged) — sizes the stage's paged-KV stack."""
        if not self.layer_types:
            return self.num_stage_layers
        return sum(1 for t in self.stage_layer_types
                   if t == "full_attention")

    @property
    def num_linear_layers(self) -> int:
        return sum(1 for t in self.stage_layer_types
                   if t == "linear_attention")

    @property
    def gdn_conv_dim(self) -> int:
        return (2 * self.linear_num_key_heads * self.linear_key_head_dim
                + self.linear_num_value_heads * self.linear_value_head_dim)

    # Pipeline-parallel stage slice (rank-aware model construction like the
    # reference's per-stage layer builds, qwen2.py:186-270). Full model by
    # default.
    first_layer: int = 0
    last_layer: int = -1              # exclusive; -1 → num_layers

    @property
    def stage_layers(self) -> Tuple[int, int]:
        last = self.num_layers if self.last_layer < 0 else self.last_layer
        return (self.first_layer, last)

    @property
    def num_stage_layers(self) -> int:
        a, b = self.stage_layers
        return b - a

    @property
    def is_first_stage(self) -> bool:
        return self.first_layer == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_layers[1] == self.num_layers


def _first_eos(v) -> Optional[int]:
    if isinstance(v, list):
        return v[0] if v else None
    return v


def _eos_tuple(v) -> Optional[Tuple[int, ...]]:
    if v is None:
        return None
    if isinstance(v, (list, tuple)):
        return tuple(v) or None
    return (v,)


def from_hf_config(hf: Dict[str, Any]) -> ModelConfig:
    """Parse an HF config.json dict into a ModelConfig."""
    arch = (hf.get("architectures")
            or (hf.get("text_config") or {}).get("architectures")
            or ["LlamaForCausalLM"])[0]
    extra: Dict[str, Any] = {}
    if arch in ("Qwen3VLForConditionalGeneration",
                "Qwen3VLMoeForConditionalGeneration"):
        vision = hf.get("vision_config") or {}
        text = dict(hf.get("text_config") or hf)
        rope_scaling = text.get("rope_scaling") or {}
        extra = dict(
            mrope_section=tuple(rope_scaling.get("mrope_section", ())),
            mrope_interleaved=True,
            image_token_id=hf.get("image_token_id",
                                  text.get("image_token_id", -1)),
            video_token_id=hf.get("video_token_id",
                                  text.get("video_token_id", -1)),
            vision_config=vision,
            deepstack_num_levels=len(
                vision.get("deepstack_visual_indexes", ())),
            mm_per_frame_video=True,
        )
        if rope_scaling.get("type") == "mrope" \
                or rope_scaling.get("rope_type") == "mrope":
            text["rope_scaling"] = None
        hf = {**text, "architectures": [arch],
              "eos_token_id": hf.get("eos_token_id",
                                     text.get("eos_token_id"))}
    if arch in ("ChatGLMModel", "ChatGLMForConditionalGeneration"):
        # ChatGLM3 legacy config layout (reference models/chatglm.py):
        # kv_channels=head_dim, rotary over head_dim/2 interleaved
        # (RotaryEmbedding(..., is_neox_style=False)), fused
        # query_key_value / dense_h_to_4h handled by chatglm_rules.
        n_heads = hf["num_attention_heads"]
        hf = {
            "architectures": [arch],
            "vocab_size": hf["padded_vocab_size"],
            "hidden_size": hf["hidden_size"],
            "num_hidden_layers": hf["num_layers"],
            "num_attention_heads": n_heads,
            "num_key_value_heads": (hf.get("multi_query_group_num", n_heads)
                                    if hf.get("multi_query_attention", False)
                                    else n_heads),
            "head_dim": hf.get("kv_channels",
                               hf["hidden_size"] // n_heads),
            "intermediate_size": hf["ffn_hidden_size"],
            "rms_norm_eps": hf.get("layernorm_epsilon", 1e-5),
            "rope_theta": 10000.0 * hf.get("rope_ratio", 1.0),
            "max_position_embeddings": hf.get("seq_length", 8192),
            "attention_bias": bool(hf.get("add_qkv_bias", False)
                                   or hf.get("add_bias_linear", False)),
            "partial_rotary_factor": 0.5,
            "tie_word_embeddings": False,
            "eos_token_id": hf.get("eos_token_id"),
        }
    if arch == "KimiK25ForConditionalGeneration":
        # DeepSeek-V3 backbone under text_config; vision dict + the media
        # placeholder (often OUTSIDE the LM vocab) at top level. Positions
        # are plain 1-D — no mrope (reference kimi_k25.py).
        vision = dict(hf.get("vision_config") or {})
        text = dict(hf.get("text_config") or hf)
        extra = dict(
            image_token_id=hf.get("media_placeholder_token_id", -1),
            vision_config=vision,
        )
        hf = {**text, "architectures": [arch],
              "eos_token_id": hf.get("eos_token_id",
                                     text.get("eos_token_id"))}
    if arch in ("Qwen2_5_VLForConditionalGeneration",
                "Qwen2VLForConditionalGeneration"):
        # VL configs nest the LM under text_config (newer transformers) or
        # keep it flat (older checkpoints); vision is always a sub-dict.
        vision = hf.get("vision_config") or {}
        text = dict(hf.get("text_config") or hf)
        rope_scaling = text.get("rope_scaling") or {}
        extra = dict(
            mrope_section=tuple(rope_scaling.get("mrope_section", ())),
            image_token_id=hf.get("image_token_id",
                                  text.get("image_token_id", -1)),
            video_token_id=hf.get("video_token_id",
                                  text.get("video_token_id", -1)),
            vision_config=vision,
        )
        # mrope tables are plain rope tables; drop the marker type so the
        # table builder doesn't choke, keep the section split in extra.
        if rope_scaling.get("type") == "mrope" \
                or rope_scaling.get("rope_type") == "mrope":
            text["rope_scaling"] = None
        hf = {**text, "architectures": [arch],
              "eos_token_id": hf.get("eos_token_id",
                                     text.get("eos_token_id"))}
    if arch in ("Qwen3NextForCausalLM", "Qwen3_5ForCausalLM",
                "Qwen3_5MoeForCausalLM", "Qwen3_5ForConditionalGeneration",
                "Qwen3_5MoeForConditionalGeneration"):
        # Real Qwen3.5 checkpoints use the *ForConditionalGeneration arch
        # string and may nest the LM under text_config (reference reads
        # attrs with a text_config fallback, model_loader.py:180-201).
        text = dict(hf.get("text_config") or hf)
        extra = dict(
            layer_types=tuple(text.get("layer_types", ())),
            linear_num_value_heads=text.get("linear_num_value_heads", 0),
            linear_num_key_heads=text.get("linear_num_key_heads", 0),
            linear_key_head_dim=text.get("linear_key_head_dim", 0),
            linear_value_head_dim=text.get("linear_value_head_dim", 0),
            linear_conv_kernel_dim=text.get("linear_conv_kernel_dim", 4),
        )
        hf = {**text, "architectures": [arch],
              "eos_token_id": hf.get("eos_token_id",
                                     text.get("eos_token_id"))}
    num_heads = hf["num_attention_heads"]
    hidden = hf["hidden_size"]
    head_dim = hf.get("head_dim") or hidden // num_heads
    qk_norm = arch in ("Qwen3ForCausalLM", "Qwen3MoeForCausalLM",
                       "Qwen3NextForCausalLM", "Qwen3_5ForCausalLM",
                       "Qwen3_5MoeForCausalLM",
                       "Qwen3VLForConditionalGeneration",
                       "Qwen3VLMoeForConditionalGeneration")
    is_glm4 = arch in ("Glm4ForCausalLM",)
    # GLM-4 base / ChatGLM3: interleaved partial rotary like GLM4 but
    # WITHOUT the sandwich norms
    is_glm = arch in ("GlmForCausalLM", "ChatGLMModel",
                      "ChatGLMForConditionalGeneration")
    # HF's Qwen2-family attention is bias=True UNCONDITIONALLY
    # (modeling_qwen2.py nn.Linear(..., bias=True)): the checkpoint
    # always carries q/k/v biases even when config.json says
    # attention_bias=false, so the config key must not be trusted for
    # these archs (a false value would shrink our param template and the
    # loader would reject the checkpoint's bias tensors). The reverse
    # direction is safe: if a nonstandard bias-free export ever omits the
    # tensors, the loader leaves the template's zero biases in place —
    # mathematically identical to no bias.
    if arch in ("Qwen2ForCausalLM", "Qwen2MoeForCausalLM",
                "Qwen2_5_VLForConditionalGeneration",
                "Qwen2VLForConditionalGeneration"):
        attention_bias = True
    else:
        attention_bias = hf.get("attention_bias", False)
    return ModelConfig(
        architecture=arch,
        vocab_size=hf["vocab_size"],
        hidden_size=hidden,
        num_layers=hf["num_hidden_layers"],
        num_heads=num_heads,
        num_kv_heads=hf.get("num_key_value_heads", num_heads),
        head_dim=head_dim,
        intermediate_size=hf["intermediate_size"],
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        rope_theta=hf.get("rope_theta", 10000.0),
        rope_scaling=hf.get("rope_scaling"),
        max_position=hf.get("max_position_embeddings", 8192),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        attention_bias=attention_bias,
        qk_norm=qk_norm,
        partial_rotary_factor=hf.get("partial_rotary_factor", 1.0) or 1.0,
        rope_interleaved=is_glm4 or is_glm,
        sandwich_norms=is_glm4,
        eos_token_id=_eos_tuple(hf.get("eos_token_id")),
        bos_token_id=_first_eos(hf.get("bos_token_id")),
        hidden_act=hf.get("hidden_act", "silu"),
        num_experts=hf.get("num_experts",
                           hf.get("num_local_experts",
                                  hf.get("n_routed_experts", 0)) or 0),
        num_experts_per_tok=hf.get("num_experts_per_tok", 0) or 0,
        moe_intermediate_size=hf.get("moe_intermediate_size", 0) or 0,
        norm_topk_prob=hf.get("norm_topk_prob", True),
        decoder_sparse_step=hf.get("decoder_sparse_step", 1),
        mlp_only_layers=tuple(hf.get("mlp_only_layers", []) or []),
        shared_expert_intermediate_size=hf.get(
            "shared_expert_intermediate_size", 0) or 0,
        q_lora_rank=hf.get("q_lora_rank", 0) or 0,
        kv_lora_rank=hf.get("kv_lora_rank", 0) or 0,
        qk_nope_head_dim=hf.get("qk_nope_head_dim", 0) or 0,
        qk_rope_head_dim=hf.get("qk_rope_head_dim", 0) or 0,
        v_head_dim=hf.get("v_head_dim", 0) or 0,
        first_k_dense_replace=hf.get("first_k_dense_replace", 0) or 0,
        n_shared_experts=hf.get("n_shared_experts", 0) or 0,
        routed_scaling_factor=hf.get("routed_scaling_factor", 1.0) or 1.0,
        n_group=hf.get("n_group", 0) or 0,
        topk_group=hf.get("topk_group", 0) or 0,
        index_n_heads=hf.get("index_n_heads", 0) or 0,
        index_head_dim=hf.get("index_head_dim", 0) or 0,
        index_topk=hf.get("index_topk", 0) or 0,
        scoring_func=hf.get("scoring_func", "softmax") or "softmax",
        topk_method=hf.get("topk_method", "greedy") or "greedy",
        **extra,
    )
