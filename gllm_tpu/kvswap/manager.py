"""KV swap manager: the scheduler<->runner bridge for the host tier.

The scheduler and memory manager are pure host bookkeeping — they must
never touch the device. So, exactly like the hybrid models' SSM slot
intents, swap decisions are recorded here as **intents** and the runner
drains them at dispatch time via :meth:`KVSwapManager.apply`, BEFORE the
step program:

- gathers (swap-out / prefix spill) read their source pages ahead of the
  forward that may overwrite them — device program order makes the copy
  consistent even though the scheduler already freed (and possibly
  re-minted) the page ids;
- scatters (swap-in / prefix restore) land their pages before the
  forward reads them.

In-flight tracking: host pages belonging to a dispatched-but-not-landed
gather are pinned (never evicted, frees deferred), and device pages with
a queued restore are remembered so a re-mint of such a page can never
spill its not-yet-written content to the host tier.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Set, Tuple

import numpy as np

from gllm_tpu.faults import InjectedFault
from gllm_tpu.kvswap.engine import SwapEngine
from gllm_tpu.kvswap.host_pool import HostKVPool
from gllm_tpu.obs import metrics as obs
from gllm_tpu.utils import cdiv

logger = logging.getLogger(__name__)

# Host-tier metrics (docs/kv_offload.md, docs/observability.md).
_M_SWAP_OUT = obs.counter(
    "gllm_kvswap_swap_out_total",
    "sequences preempted by swapping their KV to the host tier")
_M_SWAP_IN = obs.counter(
    "gllm_kvswap_swap_in_total",
    "sequences resumed by swapping KV back in (zero re-prefill)")
_M_PAGES = obs.counter("gllm_kvswap_pages_total",
                       "KV pages transferred device<->host", ("dir",))
_M_BYTES = obs.counter(
    "gllm_kvswap_transfer_bytes_total",
    "KV bytes transferred device<->host (page count x the pool's "
    "per-page bytes, which reflect the cache storage dtype — int8 "
    "pages move half the bf16 bytes plus their scale rows)", ("dir",))
_M_SPILL = obs.counter(
    "gllm_kvswap_prefix_spill_pages_total",
    "refcount-0 prefix pages spilled host-side on HBM eviction")
_M_RESTORE = obs.counter(
    "gllm_kvswap_prefix_restore_pages_total",
    "host-tier prefix pages restored into HBM by match_prefix")
_M_FALLBACK = obs.counter(
    "gllm_kvswap_recompute_fallbacks_total",
    "preemptions that fell back to free-and-recompute (host pool full)")
_M_CANARY = obs.counter(
    "gllm_kvswap_host_canary_misses_total",
    "host-tier digest hits rejected by the canary check (treated as miss)")
_M_HOST = obs.gauge("gllm_kvswap_host_pool_pages",
                    "host KV pool pages by state", ("state",))
_M_HOST_USED = obs.gauge(
    "gllm_kvswap_host_pool_used_pages",
    "host KV pool occupancy (pinned sequence pages + resident prefix "
    "pages); the unlabeled companion of gllm_kvswap_host_pool_pages "
    "for dashboards and autoscalers")
_M_XFER = obs.histogram(
    "gllm_kvswap_transfer_seconds",
    "host wall time of drained swap transfers per step",
    ("dir",), buckets=obs.FAST_LATENCY_BUCKETS)


class KVSwapManager:
    def __init__(self, kv_tree, page_size: int, num_host_pages: int):
        import jax
        leaves = jax.tree.leaves(kv_tree)
        if not leaves:
            raise ValueError("empty KV tree")
        num_dev_pages = {leaf.shape[1] for leaf in leaves}
        if len(num_dev_pages) != 1:
            raise ValueError(
                f"KV leaves disagree on the page axis: {num_dev_pages} — "
                "this model family cannot use the host tier")
        self.page_size = page_size
        self.pool = HostKVPool(
            [((leaf.shape[0],) + leaf.shape[2:], np.dtype(leaf.dtype))
             for leaf in leaves], num_host_pages)
        self.engine = SwapEngine()
        # queued intents, drained by the runner at dispatch time:
        # (dev, host, kind, owner_seq) — kind "seq" carries the swapped
        # sequence so a failed/quarantined transfer can revert it to
        # recompute; prefix spills carry None
        self._out: List[Tuple[List[int], List[int], str, object]] = []
        self._in: List[Tuple[List[int], List[int], str]] = []  # +kind
        # device pages whose restore scatter hasn't drained: a re-mint of
        # one must not spill its (not yet written) content
        self._pending_restore_dev: Set[int] = set()
        # host pages released while their gather was in flight: freed
        # only after the fetch lands (their slot must not be re-tenanted
        # under a pending write)
        self._free_after_fetch: Set[int] = set()
        # Tiered prefix store (gllm_tpu/kvstore.TieredPrefixManager):
        # attached by the engine when disk/peer tiers are configured.
        # None keeps every probe path byte-identical two-level legacy.
        self.tiers = None
        # which tier served the last match_host_prefix hit ("host" |
        # "disk" | "peer") — read by PrefixMemoryManager for the
        # per-tier steptrace attribution, valid until the next probe
        self.last_hit_tier: Optional[str] = None
        # device pages the LAST apply() scattered host data into — their
        # scales came from the host tier, so the runner's int8
        # minted-page scale reset must skip them (consumed once, so a
        # dispatch with no swap work never skips on page ids recycled
        # from an older drain)
        self.last_scatter_dev: Set[int] = set()
        self._update_gauges()

    # ---- sizing -----------------------------------------------------------

    @staticmethod
    def host_pages_for(kv_tree, gib: float) -> int:
        """How many host pages fit in ``gib`` GiB for this KV layout."""
        import jax
        per = sum(
            int(np.prod((leaf.shape[0],) + leaf.shape[2:]))
            * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(kv_tree))
        return int(gib * (1 << 30) // per) if per else 0

    # ---- scheduler API: swap-based preemption -----------------------------

    def try_swap_out(self, seq, mm) -> bool:
        """Swap ``seq``'s computed pages to the host tier instead of
        recomputing. On success the seq is SWAPPED with its host pages
        recorded; on failure (pool full / nothing computed) nothing
        changed and the caller falls back to free-and-recompute."""
        n = cdiv(seq.num_computed_tokens, self.page_size)
        if n <= 0 or n > len(seq.page_table):
            return False
        host = self.pool.allocate(n)
        if host is None:
            _M_FALLBACK.inc()
            return False
        dev = list(seq.page_table[:n])
        self.pool.pin(host)              # in-flight until the fetch lands
        self._out.append((dev, host, "seq", seq))
        mm.free_seq(seq)                 # device refcounts / page reuse
        seq.swap_out(host)
        _M_SWAP_OUT.inc()
        _M_PAGES.inc(n, dir="out")
        _M_BYTES.inc(n * self.pool.bytes_per_page, dir="out")
        self._update_gauges()
        return True

    def record_swap_in(self, seq) -> None:
        """Called at re-admission, after fresh device pages were
        allocated: queue the host->device restore covering the swapped
        prefix of ``seq.page_table``."""
        host = seq.swap_host_pages
        seq.swap_host_pages = None
        dev = list(seq.page_table[:len(host)])
        assert len(dev) == len(host), (len(dev), len(host))
        self._in.append((host, dev, "seq"))
        self._pending_restore_dev.update(dev)
        _M_SWAP_IN.inc()
        _M_PAGES.inc(len(host), dir="in")
        _M_BYTES.inc(len(host) * self.pool.bytes_per_page, dir="in")

    def release_seq(self, seq) -> None:
        """Free a swapped-out seq's host pages (abort / finish without
        resume)."""
        host = seq.swap_host_pages
        seq.swap_host_pages = None
        if host:
            self._free_host_pages(host)
            self._update_gauges()

    # ---- memory-manager API: prefix spill tier ----------------------------

    def spill_prefix(self, dev_page: int, digest: bytes, canary,
                     parent: Optional[bytes] = None) -> None:
        """A refcount-0 cached page is being re-minted for new content —
        copy it to the host tier keyed by the same digest. ``parent``
        (the chain-predecessor digest) rides along so a later demotion
        to the disk tier keeps the read-ahead edges."""
        if dev_page in self._pending_restore_dev:
            return   # its content hasn't landed on device yet
        host = self.pool.allocate(1)
        if host is None:
            return   # pool full of pinned pages; drop the spill
        self.pool.pin(host)
        self._out.append(([dev_page], host, "prefix", None))
        self.pool.put_prefix(host[0], digest, canary, parent=parent)
        _M_SPILL.inc()
        _M_PAGES.inc(dir="out")
        _M_BYTES.inc(self.pool.bytes_per_page, dir="out")
        self._update_gauges()

    def match_host_prefix(self, digest: bytes, tokens) -> Optional[int]:
        """Prefix probe below HBM, in tier order: host pool (canary-
        verified; a mismatch counts and misses, dropping the entry),
        then — when lower tiers are attached — disk and peers, whose
        hits are staged INTO the host pool so the returned page is
        always a host page id the normal restore path can carry.
        ``last_hit_tier`` records which tier served it.

        The returned page comes back PINNED (probe pin): the caller's
        next step — minting a device page — can itself evict from this
        pool (the mint's spill allocates a host page), and an unpinned
        hit would be a legal victim, letting the spill re-tenant it
        before the restore reads it. The caller must
        ``release_probe_pin`` once ``restore_prefix`` holds its own pin
        (or on bail-out)."""
        self.last_hit_tier = None
        page = None
        if self.pool.hash_to_page.get(digest) is not None:
            page = self.pool.match_prefix(digest, tokens)
            if page is None:
                _M_CANARY.inc()
            else:
                self.last_hit_tier = "host"
        if page is None and self.tiers is not None:
            staged = self.tiers.probe(digest, tokens)
            if staged is not None:
                page, self.last_hit_tier = staged
                self._update_gauges()
        if page is not None:
            self.pool.pin([page])
        return page

    def release_probe_pin(self, page: int) -> None:
        self.pool.unpin([page])

    def restore_prefix(self, host_page: int, dev_page: int) -> None:
        """Queue a host->device copy of a cached prefix page into a
        freshly minted device page (the host copy stays cached)."""
        self.pool.pin([host_page])       # survive eviction until drained
        self._in.append(([host_page], [dev_page], "prefix"))
        self._pending_restore_dev.add(dev_page)
        _M_RESTORE.inc()
        _M_PAGES.inc(dir="in")
        _M_BYTES.inc(self.pool.bytes_per_page, dir="in")

    # ---- runner API --------------------------------------------------------

    @property
    def has_work(self) -> bool:
        return bool(self._out or self._in or self.engine._pending)

    def consume_last_scatter_dev(self) -> Set[int]:
        out, self.last_scatter_dev = self.last_scatter_dev, set()
        return out

    def apply(self, kv):
        """Drain queued intents against the runner's KV; returns the new
        KV pytree. Must run at dispatch time, before the step program."""
        if self.engine._pending:
            # land the PREVIOUS drain's gathers (double buffer)
            t0 = time.monotonic()
            self._materialize()
            _M_XFER.observe(time.monotonic() - t0, dir="out")
        outs, self._out = self._out, []
        ins, self._in = self._in, []
        self.last_scatter_dev = {p for _, d, _ in ins for p in d}
        if outs:
            dev = [p for d, _, _, _ in outs for p in d]
            host = [p for _, h, _, _ in outs for p in h]
            try:
                self.engine.gather(kv, dev, host)
            except InjectedFault:
                # transfer plane failed before any data moved: revert
                # every queued swap-out to the legacy recompute path and
                # drop the spills — nobody may ever read the unwritten
                # host slots (docs/robustness.md)
                logger.warning("kvswap gather failed; reverting %d "
                               "intents to recompute", len(outs))
                self._drop_out_intents(outs)
        if ins:
            needed = {p for h, _, _ in ins for p in h}
            if needed & self.engine.pending_host_pages():
                # swap-out and swap-in of the same page in one pass
                # (admission thrash): block on the fetch so the scatter
                # reads real data — this is the SLOW outbound case, so
                # it must land in the dir="out" histogram too
                t0 = time.monotonic()
                self._materialize()
                _M_XFER.observe(time.monotonic() - t0, dir="out")
            t0 = time.monotonic()
            host = [p for h, _, _ in ins for p in h]
            dev = [p for _, d, _ in ins for p in d]
            kv = self.engine.scatter(kv, dev, self.pool, host)
            _M_XFER.observe(time.monotonic() - t0, dir="in")
            for h_pages, d_pages, kind in ins:
                self._pending_restore_dev.difference_update(d_pages)
                if kind == "seq":
                    # the resumed seq's host copy is dead weight now
                    self._free_host_pages(h_pages)
                else:
                    self.pool.unpin(h_pages)
        self._update_gauges()
        return kv

    # ---- fault recovery ----------------------------------------------------

    def _drop_out_intents(self, outs) -> None:
        """Undo queued (never-dispatched) device→host intents: their host
        slots hold no data. Seq swap-outs revert to recompute (the seq
        re-prefills from scratch on re-admission); prefix spills lose
        their digest key so a zeroed page can never be served."""
        from gllm_tpu.sequence import SequenceStatus
        for dev, host, kind, seq in outs:
            self.pool.unpin(host)
            if kind == "seq" and seq is not None:
                if seq.swap_host_pages:
                    seq.swap_host_pages = None
                    if seq.status is SequenceStatus.SWAPPED:
                        seq.preempt()
                    _M_FALLBACK.inc()
                    self._free_host_pages(host)
                # else: an abort already routed through release_seq and
                # freed these host pages — don't double-free
            else:
                for p in host:
                    self.pool.drop_prefix(p)
                self._free_host_pages(host)
        self._update_gauges()

    def quarantine(self) -> None:
        """Step-failure rollback (LLM.quarantine_step_failure): drop every
        QUEUED transfer intent — the dispatch they were waiting for will
        never run, and the pages they reference may be freed/re-minted by
        the quarantine. Already-dispatched gathers (``engine._pending``)
        are left to land normally: they read consistent pre-overwrite
        data and their host pages free through ``_free_after_fetch``."""
        outs, self._out = self._out, []
        ins, self._in = self._in, []
        self._drop_out_intents(outs)
        for host, dev, kind in ins:
            self._pending_restore_dev.difference_update(dev)
            if kind == "seq":
                # record_swap_in already detached these pages from their
                # seq; the restore will never run, so free the copy
                self._free_host_pages(host)
            else:
                self.pool.unpin(host)
        self.last_scatter_dev.clear()
        self._update_gauges()

    # ---- internals ---------------------------------------------------------

    def _materialize(self) -> None:
        pending = [hp for _, hp, n in self.engine._pending for hp in hp[:n]]
        self.engine.materialize(self.pool,
                                skip_free=self._free_after_fetch)
        self.pool.unpin(pending)
        if self._free_after_fetch:
            self.pool.free(list(self._free_after_fetch))
            self._free_after_fetch.clear()

    def _free_host_pages(self, pages) -> None:
        pending = self.engine.pending_host_pages()
        now = [p for p in pages if p not in pending]
        self._free_after_fetch.update(p for p in pages if p in pending)
        if now:
            self.pool.free(now)

    def _update_gauges(self) -> None:
        _M_HOST.set(self.pool.num_free, state="free")
        _M_HOST.set(self.pool.num_used, state="used")
        _M_HOST_USED.set(self.pool.num_used)
