"""Short, timeout-bounded probes of every Pallas kernel on the REAL chip.

VERDICT r02 weak #4: ``gdn_chunk_scan`` (and the multi-step fused decode
loop) had never executed on real hardware while being auto-selected on
TPU. This script runs each Pallas kernel — ragged prefill attention,
decode attention, packed-KV, MLA, GDN chunk-scan — plus a multi-step
fused decode engine step, one at a time with a hard per-probe deadline,
and prints one status line per probe. A device-side stall therefore
names its kernel instead of wedging a full benchmark.

Run ONLY when the axon tunnel answers (single-tenant):
    timeout 600 python benchmarks/chip_probes.py          # all probes
    timeout 180 python benchmarks/chip_probes.py gdn      # one probe

Each probe runs in a fresh subprocess with its own timeout so a hung
kernel cannot take the supervisor (or the tunnel session) down with it.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PROBE_TIMEOUT_S = 150


# ---------------------------------------------------------------------------
# individual probes (run inside the child process)
# ---------------------------------------------------------------------------

def _fetch(x):
    """Value fetch — under axon only a fetch proves device work finished
    (block_until_ready does not actually wait, verify SKILL.md)."""
    import numpy as np
    return np.asarray(x)


def probe_ragged():
    """Ragged paged prefill attention, aligned head_dim=128."""
    import jax.numpy as jnp
    import numpy as np
    from gllm_tpu.ops.pallas.ragged_attention import ragged_paged_attention

    P, ps, Hkv, Hq, D = 64, 16, 2, 4, 128
    T = 128
    k_cache = jnp.zeros((P, ps, Hkv, D), jnp.bfloat16)
    v_cache = jnp.zeros((P, ps, Hkv, D), jnp.bfloat16)
    q = jnp.ones((T, Hq, D), jnp.bfloat16)
    page_table = jnp.zeros((2, 16), jnp.int32)
    cu_q = jnp.asarray([0, 64, 128], jnp.int32)
    kv_lens = jnp.asarray([64, 64], jnp.int32)
    import jax
    out = ragged_paged_attention(q, k_cache, v_cache, cu_q, kv_lens,
                                 page_table, scale=D ** -0.5,
                                 interpret=jax.default_backend() == "cpu")
    assert _fetch(out).shape == (T, Hq, D)


def probe_decode():
    """Decode attention (one q token per seq)."""
    import jax.numpy as jnp
    from gllm_tpu.ops.pallas.decode_attention import paged_decode_attention

    P, ps, Hkv, Hq, D = 64, 16, 2, 4, 128
    S = 8
    k_cache = jnp.zeros((P, ps, Hkv, D), jnp.bfloat16)
    v_cache = jnp.zeros((P, ps, Hkv, D), jnp.bfloat16)
    q = jnp.ones((S, Hq, D), jnp.bfloat16)
    page_table = jnp.zeros((S, 16), jnp.int32)
    kv_lens = jnp.full((S,), 48, jnp.int32)
    import jax
    out = paged_decode_attention(q, k_cache, v_cache, kv_lens, page_table,
                                 scale=D ** -0.5,
                                 interpret=jax.default_backend() == "cpu")
    assert _fetch(out).shape == (S, Hq, D)


def probe_gdn():
    """gdn_chunk_scan with aligned Dk=Dv=128 (the auto-selected config)."""
    import jax.numpy as jnp
    from gllm_tpu.ops.gdn import chunk_gated_delta_rule

    S, T, H, D = 2, 128, 2, 128
    import jax
    key = jax.random.key(0)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (S, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (S, T, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (S, T, H, D), jnp.float32)
    g = -jnp.abs(jax.random.normal(ks[3], (S, T, H), jnp.float32)) * 0.1
    beta = jax.nn.sigmoid(jax.random.normal(ks[4], (S, T, H), jnp.float32))
    out_p, st_p = chunk_gated_delta_rule(q, k, v, g, beta, impl="pallas")
    out_x, st_x = chunk_gated_delta_rule(q, k, v, g, beta, impl="xla")
    import numpy as np
    np.testing.assert_allclose(_fetch(out_p), _fetch(out_x), atol=2e-2,
                               rtol=2e-2)
    np.testing.assert_allclose(_fetch(st_p), _fetch(st_x), atol=2e-2,
                               rtol=2e-2)


def probe_multistep():
    """Multi-step fused decode through the real engine (the round-2
    device-stall suspect): 3-step fused loop on a tiny dummy model."""
    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.models.config import ModelConfig
    from gllm_tpu.sampling_params import SamplingParams

    mcfg = ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=512, hidden_size=256,
        num_layers=2, num_heads=2, num_kv_heads=2, head_dim=128,
        intermediate_size=512, max_position=512)
    llm = LLM(config=EngineConfig(
        load_format="dummy", dtype="bfloat16", max_model_len=256,
        overlap_scheduling=True, multi_step_decode=3,
        cache=CacheConfig(page_size=16, num_pages=64)),
        model_cfg=mcfg)
    outs = llm.generate(
        prompt_token_ids=[[3, 5, 7], [11, 13]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=24,
                                       ignore_eos=True))
    assert all(len(o.output_token_ids) == 24 for o in outs)


def probe_mla():
    """Absorbed-MLA decode via the engine (DeepSeek-shaped tiny config)."""
    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.models.config import ModelConfig
    from gllm_tpu.sampling_params import SamplingParams

    mcfg = ModelConfig(
        architecture="DeepseekV2ForCausalLM", vocab_size=512,
        hidden_size=256, num_layers=2, num_heads=4, num_kv_heads=4,
        head_dim=64, intermediate_size=512, max_position=512,
        kv_lora_rank=512, qk_nope_head_dim=64,
        qk_rope_head_dim=32, v_head_dim=64,
        first_k_dense_replace=2)      # all-dense: probe targets MLA only
    llm = LLM(config=EngineConfig(
        load_format="dummy", dtype="bfloat16", max_model_len=256,
        attention_impl="pallas",
        cache=CacheConfig(page_size=16, num_pages=64)),
        model_cfg=mcfg)
    outs = llm.generate(
        prompt_token_ids=[[3, 5, 7]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
    assert len(outs[0].output_token_ids) == 8


def _headline_model_cfg():
    """Tiny model with the HEADLINE bench head geometry (Llama-3.2-1B:
    head_dim 64, GQA 32/8 → packed-KV pack=2) — shared by the probes that
    must cover the exact attention configuration bench.py will serve."""
    from gllm_tpu.models.config import ModelConfig
    return ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=512, hidden_size=256,
        num_layers=2, num_heads=32, num_kv_heads=8, head_dim=64,
        intermediate_size=512, max_position=512, rope_theta=500000.0,
        tie_word_embeddings=True)


def probe_bench_shape():
    """The headline bench geometry through the real engine in bfloat16 —
    the exact attention configuration bench.py will serve, so a Mosaic
    surprise shows up here, named, instead of inside a 600 s bench
    budget."""
    from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams

    llm = LLM(config=EngineConfig(
        load_format="dummy", dtype="bfloat16", max_model_len=256,
        scheduler=SchedulerConfig(max_prefill_tokens=128,
                                  max_decode_seqs=16),
        cache=CacheConfig(page_size=16, num_pages=128)),
        model_cfg=_headline_model_cfg())
    outs = llm.generate(
        prompt_token_ids=[[3, 5, 7] * 20, [11, 13]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=16,
                                       ignore_eos=True))
    assert all(len(o.output_token_ids) == 16 for o in outs)


def probe_spec():
    """Speculative decoding through the real engine on the headline bench
    head geometry (packed-KV D=64 GQA): the verify program (gathered
    rows + spec_adjust_logits + spec_verify) is its own jit signature —
    compile and run it on chip with drafts actually accepted, so a
    Mosaic/compile surprise in the spec path shows up named."""
    from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams

    llm = LLM(config=EngineConfig(
        load_format="dummy", dtype="bfloat16", max_model_len=256,
        spec_decode="ngram", spec_k=4, spec_ngram=2,
        scheduler=SchedulerConfig(max_prefill_tokens=128,
                                  max_decode_seqs=16),
        cache=CacheConfig(page_size=16, num_pages=128)),
        model_cfg=_headline_model_cfg())
    outs = llm.generate(
        prompt_token_ids=[[3, 5, 7, 3, 5, 7, 3, 5], [11, 13]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=16,
                                       ignore_eos=True))
    assert all(len(o.output_token_ids) == 16 for o in outs)
    st = llm.scheduler.spec_stats
    # a greedy loop on this repetitive prompt MUST accept drafts — a
    # verify program that silently rejects everything is exactly the
    # on-chip miscompile this probe exists to name (CPU oracle: 14/14)
    assert st["proposed"] > 0 and st["accepted"] > 0, st


PROBES = {
    "ragged": probe_ragged,
    "decode": probe_decode,
    "gdn": probe_gdn,
    "multistep": probe_multistep,
    "mla": probe_mla,
    "bench_shape": probe_bench_shape,
    "spec": probe_spec,
}


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--inner":
        name = sys.argv[2]
        import faulthandler
        faulthandler.dump_traceback_later(PROBE_TIMEOUT_S - 10, exit=False)
        # share one persistent compile cache with bench.py so probe
        # compiles survive tunnel wedges and later benefit the bench
        from gllm_tpu.utils import enable_compilation_cache
        enable_compilation_cache(os.path.join(REPO, ".jax_cache"))
        t0 = time.monotonic()
        PROBES[name]()
        print(f"[probe inner] {name} ok {time.monotonic() - t0:.1f}s",
              flush=True)
        return

    wanted = sys.argv[1:] or list(PROBES)
    results = {}
    for name in wanted:
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--inner",
                 name],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, timeout=PROBE_TIMEOUT_S)
            ok = proc.returncode == 0
            tail = proc.stdout[-2000:]
        except subprocess.TimeoutExpired as e:
            ok, tail = False, "TIMEOUT\n" + str(e.stdout or "")[-2000:]
        dt = time.monotonic() - t0
        results[name] = {"ok": ok, "seconds": round(dt, 1)}
        status = "ok" if ok else "FAIL"
        print(f"[probe] {name}: {status} ({dt:.1f}s)", file=sys.stderr,
              flush=True)
        if not ok:
            sys.stderr.write(tail + "\n")
    print(json.dumps(results))
    return 0 if all(r["ok"] for r in results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
