"""Minimal Prometheus-style metrics registry (stdlib only).

The serving image ships neither ``prometheus_client`` nor fastapi, so this
is a small, thread-safe re-implementation of the subset the engine needs:
Counter / Gauge / Histogram with fixed buckets, label support, and the
text exposition format (version 0.0.4) that Prometheus / VictoriaMetrics /
Grafana Agent scrape.

Design constraints:

- **Off the device hot path.** Every operation is a dict update under a
  lock; nothing here imports jax, touches device arrays, or changes any
  jit static argument. Instrumentation call sites pass plain Python
  numbers they already had.
- **Idempotent registration.** Modules call ``counter(...)`` at import or
  first use; re-registering the same (name, type, labelnames) returns the
  existing metric, while a conflicting re-registration raises — the smoke
  check relies on this to catch copy-paste name collisions.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "counter", "gauge", "histogram", "render", "percentile",
    "LATENCY_BUCKETS", "FAST_LATENCY_BUCKETS",
]

# Request-scale latency buckets (seconds): TTFT / e2e / queue time.
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
# Step-scale latency buckets (seconds): per-iteration collect / RTT / ITL
# — decode steps land in the 1-100 ms decades, so that range is dense.
FAST_LATENCY_BUCKETS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.035,
                        0.05, 0.075, 0.1, 0.15, 0.25, 0.5, 1.0, 2.5, 10.0)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v)) if isinstance(v, float) else str(v)


def _label_str(labelnames: Sequence[str], values: Tuple[str, ...],
               extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = [(n, v) for n, v in zip(labelnames, values)] + list(extra)
    if not pairs:
        return ""
    return ("{" + ",".join(f'{n}="{_escape_label(str(v))}"'
                           for n, v in pairs) + "}")


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], object] = {}

    def _key(self, labels: dict) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} expects labels {self.labelnames}, "
                f"got {tuple(labels)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def labels(self, **labels) -> "_Child":
        return _Child(self, self._key(labels))

    # subclasses implement _zero() and render-sample iteration

    def _cell(self, key: Tuple[str, ...]):
        v = self._values.get(key)
        if v is None:
            v = self._values[key] = self._zero()
        return v

    def clear(self) -> None:
        with self._lock:
            self._values.clear()


class _Child:
    """Bound (metric, label-values) pair; forwards the write API."""

    def __init__(self, metric: _Metric, key: Tuple[str, ...]):
        self._m = metric
        self._k = key

    def inc(self, amount: float = 1.0) -> None:
        self._m._inc(self._k, amount)

    def set(self, value: float) -> None:
        self._m._set(self._k, value)

    def observe(self, value: float) -> None:
        self._m._observe(self._k, value)

    def get(self):
        return self._m._get(self._k)


class Counter(_Metric):
    kind = "counter"

    def _zero(self):
        return 0.0

    def inc(self, amount: float = 1.0, **labels) -> None:
        self._inc(self._key(labels), amount)

    def _inc(self, key, amount: float) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._values[key] = self._cell(key) + amount

    def get(self, **labels) -> float:
        return self._get(self._key(labels))

    def _get(self, key) -> float:
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self):
        with self._lock:
            items = list(self._values.items())
        for key, v in items:
            yield self.name, _label_str(self.labelnames, key), v


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self._set(self._key(labels), value)

    def _set(self, key, value: float) -> None:
        with self._lock:
            self._values[key] = float(value)

    def _inc(self, key, amount: float) -> None:
        with self._lock:
            self._values[key] = self._cell(key) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self._inc(self._key(labels), -amount)


class _HistCell:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets   # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(float(x) for x in buckets))
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"bad buckets for {name}: {buckets}")
        self.buckets = b                 # upper bounds, +Inf implicit

    def _zero(self):
        return _HistCell(len(self.buckets) + 1)

    def observe(self, value: float, **labels) -> None:
        self._observe(self._key(labels), value)

    def _observe(self, key, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            cell = self._cell(key)
            cell.counts[i] += 1
            cell.sum += value
            cell.count += 1

    def snapshot(self, **labels):
        """(bucket_counts, sum, count) copy — diff two snapshots to get
        the observations of a bounded window (bench measured pass)."""
        key = self._key(labels)
        with self._lock:
            cell = self._values.get(key)
            if cell is None:
                return ([0] * (len(self.buckets) + 1), 0.0, 0)
            return (list(cell.counts), cell.sum, cell.count)

    def samples(self):
        with self._lock:
            items = [(k, list(c.counts), c.sum, c.count)
                     for k, c in self._values.items()]
        for key, counts, total, count in items:
            cum = 0
            for ub, n in zip(self.buckets + (math.inf,), counts):
                cum += n
                yield (self.name + "_bucket",
                       _label_str(self.labelnames, key,
                                  (("le", _fmt(ub)),)), cum)
            yield (self.name + "_sum",
                   _label_str(self.labelnames, key), total)
            yield (self.name + "_count",
                   _label_str(self.labelnames, key), count)


def percentile(hist: Histogram, q: float, before=None, **labels
               ) -> Optional[float]:
    """Estimate the q-quantile (0..1) from bucket counts, linearly
    interpolated within the winning bucket. ``before`` subtracts an
    earlier ``snapshot()`` so the estimate covers only the window since.
    Returns None when the window holds no observations; the top bucket
    clamps to its lower bound (open-ended +Inf)."""
    counts, _, count = hist.snapshot(**labels)
    if before is not None:
        bcounts, _, bcount = before
        counts = [a - b for a, b in zip(counts, bcounts)]
        count -= bcount
    if count <= 0:
        return None
    target = q * count
    cum = 0
    bounds = (0.0,) + hist.buckets
    for i, n in enumerate(counts):
        if cum + n >= target and n > 0:
            lo = bounds[i]
            hi = hist.buckets[i] if i < len(hist.buckets) else bounds[i]
            frac = (target - cum) / n
            return lo + (hi - lo) * frac
        cum += n
    return bounds[-1]


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            cur = self._metrics.get(metric.name)
            if cur is not None:
                if (type(cur) is not type(metric)
                        or cur.labelnames != metric.labelnames
                        or (isinstance(cur, Histogram)
                            and cur.buckets != metric.buckets)):
                    raise ValueError(
                        f"metric {metric.name!r} already registered with "
                        f"a different type/labels/buckets ({cur.kind} "
                        f"{cur.labelnames} vs {metric.kind} "
                        f"{metric.labelnames})")
                return cur
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def render(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        out: List[str] = []
        for m in self.metrics():
            out.append(f"# HELP {m.name} {_escape_help(m.help)}")
            out.append(f"# TYPE {m.name} {m.kind}")
            for sname, lbl, value in m.samples():
                out.append(f"{sname}{lbl} {_fmt(float(value))}")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        """Zero every metric's samples (registrations survive) — test
        isolation and bench window bracketing."""
        for m in self.metrics():
            m.clear()


REGISTRY = Registry()


def counter(name: str, help: str, labelnames: Sequence[str] = (),
            registry: Registry = None) -> Counter:
    return (registry or REGISTRY).register(Counter(name, help, labelnames))


def gauge(name: str, help: str, labelnames: Sequence[str] = (),
          registry: Registry = None) -> Gauge:
    return (registry or REGISTRY).register(Gauge(name, help, labelnames))


def histogram(name: str, help: str, labelnames: Sequence[str] = (),
              buckets: Sequence[float] = LATENCY_BUCKETS,
              registry: Registry = None) -> Histogram:
    return (registry or REGISTRY).register(
        Histogram(name, help, labelnames, buckets))


def render(registry: Registry = None) -> str:
    return (registry or REGISTRY).render()


def parse_exposition(text: str):
    """Parse exposition text back into {(sample_name, label_str): value}
    plus the set of TYPEd metric names. Used by the smoke check to assert
    every sample belongs to a declared metric and no (name, labels) pair
    repeats — not a general-purpose parser."""
    typed: Dict[str, str] = {}
    samples: Dict[Tuple[str, str], float] = {}
    dupes: List[Tuple[str, str]] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            typed[name] = kind
            continue
        if line.startswith("#"):
            continue
        body, _, value = line.rpartition(" ")
        brace = body.find("{")
        if brace >= 0:
            name, lbl = body[:brace], body[brace:]
        else:
            name, lbl = body, ""
        key = (name, lbl)
        if key in samples:
            dupes.append(key)
        samples[key] = float(value)
    return typed, samples, dupes
