"""int8 KV cache (kv_cache_dtype=int8, ISSUE 5).

Four layers of coverage, all CPU-deterministic:

- write-path units: quantized scatter roundtrip within one quantization
  step per element, and the rescale-on-grow invariant (rows written
  before a page's scale grew stay within the NEW scale's step);
- kernel parity: the Pallas decode/ragged kernels (interpret mode)
  reproduce the XLA gathered-dequant oracle EXACTLY on the same int8
  data, and the quantized XLA path stays within quantization error of
  the full-precision reference;
- capacity: the int8 cache prices >= 1.8x the bf16 page count from the
  same memory_stats budget (the acceptance criterion);
- engine e2e: flag-off ("auto") is byte-identical to an explicit
  full-precision cache dtype; flag-on passes bounded-error oracles
  (teacher-forced per-token logprob delta + greedy agreement over
  seeded prompts); the kvswap host tier round-trips int8 pages + scales
  token-identically; unsupported combos raise instead of degrading.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.ops.attention import AttentionMetadata, paged_attention
from gllm_tpu.ops.kv_cache import QMAX, write_kv, write_kv_quant
from gllm_tpu.sampling_params import SamplingParams

MODEL_KW = dict(architecture="LlamaForCausalLM", vocab_size=512,
                hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                head_dim=16, intermediate_size=128, max_position=256)


# ---- write path -----------------------------------------------------------

def _empty_quant(P=9, ps=4, H=2, D=128):
    z = jnp.zeros((P, ps, H, D), jnp.int8)
    s = jnp.zeros((P, H), jnp.float32)
    return z, z, s, s, P, ps, H, D


def _dequant(cache, scale):
    return np.asarray(cache).astype(np.float32) * \
        np.asarray(scale)[:, None, :, None]


def test_write_kv_quant_roundtrip():
    kc, vc, ks, vs, P, ps, H, D = _empty_quant()
    rng = np.random.default_rng(0)
    T = 10
    k = jnp.asarray(rng.normal(size=(T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(T, H, D)) * 3, jnp.float32)
    slots = jnp.asarray(np.arange(T) + ps, jnp.int32)     # pages 1..3
    kc, vc, ks, vs = write_kv_quant(kc, vc, ks, vs, k, v, slots, ps)
    for cache, scale, rows in ((kc, ks, k), (vc, vs, v)):
        flat = _dequant(cache, scale).reshape(P * ps, H, D)
        err = np.abs(flat[np.asarray(slots)] - np.asarray(rows))
        # one quantization step = scale/2 per element, per (page, head)
        pages = np.asarray(slots) // ps
        bound = np.asarray(scale)[pages][:, :, None] * 0.51
        assert (err <= bound).all(), err.max()
        # scales really are the per-page per-head running absmax
        amax = np.zeros((P, H))
        for t, p in enumerate(pages):
            amax[p] = np.maximum(amax[p],
                                 np.abs(np.asarray(rows[t])).max(-1))
        np.testing.assert_allclose(np.asarray(scale)[1:4],
                                   amax[1:4] / QMAX, rtol=1e-6)


def test_write_kv_quant_rescale_on_grow():
    """A later large row grows the page scale; rows quantized against
    the OLD scale must be re-quantized in place, staying within the new
    scale's quantization step (plus one re-rounding)."""
    kc, vc, ks, vs, P, ps, H, D = _empty_quant()
    rng = np.random.default_rng(1)
    small = jnp.asarray(rng.normal(size=(2, H, D)), jnp.float32)
    slots = jnp.asarray([ps, ps + 1], jnp.int32)          # page 1
    kc, vc, ks, vs = write_kv_quant(kc, vc, ks, vs, small, small, slots,
                                    ps)
    big = 25.0 * jnp.asarray(rng.normal(size=(1, H, D)), jnp.float32)
    kc, vc, ks, vs = write_kv_quant(kc, vc, ks, vs, big, big,
                                    jnp.asarray([ps + 2], jnp.int32), ps)
    flat = _dequant(kc, ks).reshape(P * ps, H, D)
    err = np.abs(flat[np.asarray(slots)] - np.asarray(small))
    bound = np.asarray(ks)[1][None, :, None] * 1.01   # rescale re-rounds
    assert (err <= bound).all(), (err.max(), np.asarray(ks)[1])
    # the grown scale serves the new row too
    err_big = np.abs(flat[ps + 2] - np.asarray(big[0]))
    assert (err_big <= np.asarray(ks)[1][:, None] * 0.51).all()


def test_write_kv_quant_zero_scale_page_zero_fills():
    """First write to a never-written page (scale 0) must zero-fill the
    stale slots via the ratio-0 rescale, not dequantize garbage."""
    kc, vc, ks, vs, P, ps, H, D = _empty_quant()
    # plant garbage bytes in page 2 with scale still 0
    kc = kc.at[2].set(jnp.ones((ps, H, D), jnp.int8) * 55)
    rows = jnp.ones((1, H, D), jnp.float32)
    kc, vc, ks, vs = write_kv_quant(kc, vc, ks, vs, rows, rows,
                                    jnp.asarray([2 * ps + 3], jnp.int32),
                                    ps)
    page = np.asarray(kc)[2]
    assert (page[:3] == 0).all()          # stale slots zeroed
    assert (page[3] != 0).any()           # the real row landed


# ---- kernel parity --------------------------------------------------------

def _quant_fixture(seed=0, H=2, D=128, ps=4, P=9):
    rng = np.random.default_rng(seed)
    T = 10
    k = jnp.asarray(rng.normal(size=(T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(T, H, D)), jnp.float32)
    slots = jnp.asarray(np.arange(T) + ps, jnp.int32)
    z = jnp.zeros((P, ps, H, D), jnp.int8)
    s = jnp.zeros((P, H), jnp.float32)
    kc, vc, ks, vs = write_kv_quant(z, z, s, s, k, v, slots, ps)
    kcf = jnp.zeros((P, ps, H, D), jnp.float32)
    vcf = jnp.zeros((P, ps, H, D), jnp.float32)
    kcf, vcf = write_kv(kcf, vcf, k, v, slots)
    kv_lens = jnp.asarray([6, 10, 0], jnp.int32)
    pt = jnp.asarray([[1, 2, 0], [1, 2, 3], [0, 0, 0]], jnp.int32)
    return (kc, vc, ks, vs), (kcf, vcf), kv_lens, pt, rng


def test_xla_quant_within_quant_error_of_fp():
    (kc, vc, ks, vs), (kcf, vcf), kv_lens, pt, rng = _quant_fixture()
    D = kc.shape[-1]
    q = jnp.asarray(rng.normal(size=(3, 4, D)), jnp.float32)
    md = AttentionMetadata(jnp.asarray([0, 1, 2, 3], jnp.int32), kv_lens,
                           pt, jnp.int32(2))
    ref = paged_attention(q, kcf, vcf, md, scale=D ** -0.5, max_q_len=1,
                          impl="xla")
    out = paged_attention(q, kc, vc, md, scale=D ** -0.5, max_q_len=1,
                          impl="xla", k_scale=ks, v_scale=vs)
    # attention output is a convex combination of values (plus softmax
    # weight shift from key error) — stays within a few value-side
    # quantization steps
    tol = 4 * float(jnp.max(vs))
    assert float(jnp.max(jnp.abs(ref - out))) < tol


@pytest.mark.parametrize("group_size", [1, 2])
def test_pallas_decode_matches_xla_on_int8(group_size):
    (kc, vc, ks, vs), _, kv_lens, pt, rng = _quant_fixture()
    D = kc.shape[-1]
    q = jnp.asarray(rng.normal(size=(3, 4, D)), jnp.bfloat16)
    md = AttentionMetadata(jnp.asarray([0, 1, 2, 3], jnp.int32), kv_lens,
                           pt, jnp.int32(2))
    from gllm_tpu.ops.pallas.decode_attention import paged_decode_attention
    x = paged_attention(q, kc, vc, md, scale=D ** -0.5, max_q_len=1,
                        impl="xla", k_scale=ks, v_scale=vs)
    p = paged_decode_attention(q, kc, vc, kv_lens, pt, scale=D ** -0.5,
                               interpret=True, group_size=group_size,
                               k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(x, np.float32),
                               np.asarray(p, np.float32), atol=2e-2)


def test_pallas_ragged_matches_xla_on_int8():
    (kc, vc, ks, vs), _, kv_lens, pt, rng = _quant_fixture()
    D = kc.shape[-1]
    q = jnp.asarray(rng.normal(size=(3, 4, D)), jnp.bfloat16)
    cu = jnp.asarray([0, 1, 3, 3], jnp.int32)      # mixed 1+2 rows
    md = AttentionMetadata(cu, kv_lens, pt, jnp.int32(2))
    from gllm_tpu.ops.pallas.ragged_attention import ragged_paged_attention
    x = paged_attention(q, kc, vc, md, scale=D ** -0.5, max_q_len=2,
                        impl="xla", k_scale=ks, v_scale=vs)
    p = ragged_paged_attention(q, kc, vc, cu, kv_lens, pt,
                               scale=D ** -0.5, interpret=True,
                               q_block=2, kv_block=8,
                               k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(x, np.float32),
                               np.asarray(p, np.float32), atol=2e-2)


def test_pallas_mqa_int8_gated():
    import re
    from gllm_tpu.ops.pallas.decode_attention import paged_decode_attention
    kc = jnp.zeros((3, 4, 1, 128), jnp.int8)
    ks = jnp.zeros((3, 1), jnp.float32)
    with pytest.raises(NotImplementedError, match=re.escape("MQA")):
        paged_decode_attention(jnp.zeros((1, 4, 128), jnp.bfloat16),
                               kc, kc, jnp.zeros(1, jnp.int32),
                               jnp.zeros((1, 2), jnp.int32), scale=1.0,
                               interpret=True, k_scale=ks, v_scale=ks)


# ---- capacity sizing ------------------------------------------------------

def _runner(kv_dtype, **cache_kw):
    from gllm_tpu.runner.runner import ModelRunner
    cfg = EngineConfig(
        load_format="dummy", dtype="bfloat16", max_model_len=128,
        max_num_seqs=4,
        scheduler=SchedulerConfig(max_prefill_tokens=32,
                                  max_decode_seqs=4),
        cache=CacheConfig(page_size=4, num_pages=32,
                          kv_cache_dtype=kv_dtype, **cache_kw))
    return ModelRunner(cfg, ModelConfig(**MODEL_KW))


def test_int8_page_capacity_at_least_1_8x(monkeypatch):
    """Acceptance criterion: from the SAME memory_stats budget, the int8
    cache must price >= 1.8x the bf16 page count (scales cost a little,
    so exactly 2x is not expected)."""
    bf16 = _runner("auto")
    q8 = _runner("int8")
    per_bf16 = bf16._kv_bytes_per_page()
    per_int8 = q8._kv_bytes_per_page()
    assert per_bf16 / per_int8 >= 1.8, (per_bf16, per_int8)

    class FakeDev:
        def memory_stats(self):
            return {"bytes_limit": 1 << 30, "bytes_in_use": 64 << 20}

    monkeypatch.setattr(jax, "local_devices", lambda: [FakeDev()])
    pages_bf16 = bf16.determine_num_pages()
    pages_int8 = q8.determine_num_pages()
    assert pages_int8 >= 1.8 * pages_bf16, (pages_bf16, pages_int8)


def test_int8_kv_cache_has_scale_leaves():
    r = _runner("int8")
    assert r.kv.k.dtype == jnp.int8 and r.kv.v.dtype == jnp.int8
    assert r.kv.k_scale is not None and r.kv.v_scale is not None
    assert r.kv.k_scale.shape == r.kv.k.shape[:2] + (r.kv.k.shape[3],)
    # page axis stays axis 1 on every leaf (kvswap relies on it)
    assert all(leaf.shape[1] == r.num_pages
               for leaf in jax.tree.leaves(r.kv))


# ---- engine e2e -----------------------------------------------------------

def _make_llm(kv_dtype="auto", num_pages=64, prefix=False, host_pages=None,
              max_prefill_tokens=32, **eng_kw):
    from gllm_tpu.engine.llm import LLM
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=128,
        max_num_seqs=8,
        scheduler=SchedulerConfig(max_prefill_tokens=max_prefill_tokens,
                                  max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=num_pages,
                          kv_cache_dtype=kv_dtype,
                          enable_prefix_caching=prefix,
                          kv_host_pool_pages=host_pages), **eng_kw)
    return LLM(config=cfg, model_cfg=ModelConfig(**MODEL_KW))


def _workload(seed=0, n=4, max_tokens=16):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(1, 500, size=int(k)).tolist()
               for k in rng.integers(12, 28, size=n)]
    mk = lambda: [SamplingParams(temperature=0.0, max_tokens=max_tokens,  # noqa
                                 ignore_eos=True) for _ in prompts]
    return prompts, mk


def _gen(llm, prompts, params):
    return [o.output_token_ids
            for o in llm.generate(prompt_token_ids=[list(p)
                                                    for p in prompts],
                                  sampling_params=params)]


def test_flag_off_byte_identity():
    """kv_cache_dtype='auto' must be byte-identical to an explicitly
    spelled full-precision cache dtype (the engine dtype) — i.e. the
    int8 plumbing is structurally inert when off."""
    prompts, mk = _workload()
    auto = _gen(_make_llm("auto"), prompts, mk())
    f32 = _gen(_make_llm("float32"), prompts, mk())
    assert auto == f32


def test_int8_bounded_error_oracles():
    """Flag-on is numerics-changing, not numerics-breaking. Oracles:

    - teacher-forced per-token logprob delta: replay the SAME token
      sequence through both engines via prompt_logprobs (no free-running
      divergence) and bound the mean/max drift of the chosen-token
      logprobs;
    - greedy agreement: over seeded prompts, the first sampled token
      (pre-divergence) agrees on a clear majority, and whole-stream
      agreement stays well above chance. The bench model is 2 random
      layers — near-tied logits — so thresholds are loose; a REAL
      regression (garbage KV) sends both metrics to ~chance (1/512).
    """
    prompts, mk = _workload(n=6)
    ref = _make_llm("auto")
    q8 = _make_llm("int8")
    o_ref = _gen(ref, prompts, mk())
    o_q8 = _gen(q8, prompts, mk())

    first_agree = np.mean([a[0] == b[0] for a, b in zip(o_ref, o_q8)])
    stream_agree = np.mean([x == y for a, b in zip(o_ref, o_q8)
                            for x, y in zip(a, b)])
    assert first_agree >= 0.5, (first_agree, o_ref, o_q8)
    assert stream_agree >= 0.4, stream_agree

    # teacher-forced logprob drift over the reference continuation
    deltas = []
    for p, cont in zip(prompts, o_ref):
        seq = list(p) + list(cont)
        sp = [SamplingParams(temperature=0.0, max_tokens=1,
                             prompt_logprobs=1, ignore_eos=True)]
        lp = [llm.generate(prompt_token_ids=[list(seq)],
                           sampling_params=list(sp))[0].prompt_logprobs
              for llm in (ref, q8)]
        a = np.asarray([t[0] for t in lp[0][1:]])
        b = np.asarray([t[0] for t in lp[1][1:]])
        deltas.append(np.abs(a - b))
    deltas = np.concatenate(deltas)
    assert deltas.mean() < 0.25, deltas.mean()
    assert np.percentile(deltas, 95) < 1.0, np.percentile(deltas, 95)


def test_int8_composes_with_overlap_and_spec_decode():
    """int8 is supported (not gated) under the decode-slot chains /
    fused multi-step path and under ngram spec decode — both must run
    end to end and agree with the plain int8 engine far above chance.

    Byte-identity is deliberately NOT the contract here: the running
    per-page absmax grid makes stored bytes depend on where prefill
    chunk boundaries fall (a later chunk that grows a page's scale
    re-rounds the earlier chunk's rows), and overlap scheduling / spec
    drafts legitimately partition writes differently from the plain
    engine (docs/kv_quantization.md). On this 2-random-layer model the
    logits are near-tied, so those byte diffs surface as occasional
    token divergence; a REAL regression (garbage KV, broken gating)
    sends agreement to ~chance (1/512)."""
    prompts, mk = _workload(n=4)
    base = _gen(_make_llm("int8"), prompts, mk())
    fused = _gen(_make_llm("int8", overlap_scheduling=True,
                           multi_step_decode=4,
                           decode_slot_batching=True,
                           chain_under_prefill=4), prompts, mk())
    spec = _gen(_make_llm("int8", spec_decode="ngram", spec_k=3),
                prompts, mk())
    for name, other in (("fused", fused), ("spec", spec)):
        assert [len(o) for o in other] == [len(b) for b in base], name
        first = np.mean([a[0] == b[0] for a, b in zip(base, other)])
        stream = np.mean([x == y for a, b in zip(base, other)
                          for x, y in zip(a, b)])
        assert first >= 0.5, (name, first, base, other)
        assert stream >= 0.4, (name, stream)


def test_int8_dp2_runs_and_agrees():
    """dp=2 with int8: the scale leaves stack on the dp axis
    (kv_cache_specs → [dp, L, P, Hkv]) and each replica's minted pages
    reset through reset_page_scales_replica. Per-replica scheduling
    partitions prefill independently of the dp=1 engine, so the
    contract is the compose test's bounded agreement, not
    byte-identity."""
    from gllm_tpu.config import ParallelConfig
    prompts, mk = _workload(n=4)
    base = _gen(_make_llm("int8"), prompts, mk())
    dp2 = _gen(_make_llm("int8", parallel=ParallelConfig(dp=2)),
               prompts, mk())
    assert [len(o) for o in dp2] == [len(b) for b in base]
    stream = np.mean([x == y for a, b in zip(base, dp2)
                      for x, y in zip(a, b)])
    assert stream >= 0.4, (stream, base, dp2)


@pytest.mark.parametrize("prefix", [False, True])
def test_int8_recycled_pages_quantize_like_fresh(prefix):
    """Pages recycled from finished sequences must quantize exactly like
    fresh pages (mint-time scale reset, runner._apply_scale_resets):
    after heavy churn the same requests are byte-identical to a fresh
    engine — quantization never depends on page-reuse history, so the
    running absmax cannot ratchet across tenants. The prefix=True arm
    pins PrefixMemoryManager._mint_page (evicting a refcount-0 cached
    page must queue the same reset the plain allocator does)."""
    churn_p, churn_mk = _workload(seed=9, n=4, max_tokens=12)
    prompts, mk = _workload(seed=3, n=2, max_tokens=12)
    llm = _make_llm("int8", num_pages=48, prefix=prefix)
    _gen(llm, churn_p, churn_mk())        # fill + free most of the pool
    got = _gen(llm, prompts, mk())
    want = _gen(_make_llm("int8", num_pages=48, prefix=prefix),
                prompts, mk())
    assert got == want


def test_int8_kvswap_swap_roundtrip_token_identical():
    """Swap-based preemption under int8: host pages carry the int8
    payload AND the scale rows; restore must be byte-transparent, so
    the pressured run reproduces the unpressured int8 run exactly.

    Prefill is kept single-chunk per prompt (the token budget exceeds
    the TOTAL prompt length, so neither packing nor admission order can
    split a prompt): byte-identity under the running-absmax grid
    requires the same write partitioning, and page pressure would
    otherwise move chunk boundaries (decode writes are single-row, so
    THEIR partitioning never differs; see docs/kv_quantization.md)."""
    import gllm_tpu.kvswap.manager  # noqa: F401 — registers the metrics
    from gllm_tpu.obs import metrics as obs
    prompts, mk = _workload(n=4, max_tokens=20)
    want = _gen(_make_llm("int8", num_pages=128, max_prefill_tokens=96),
                prompts, mk())
    pre0 = obs.REGISTRY.get("gllm_sched_preemptions_total").get()
    in0 = obs.REGISTRY.get("gllm_kvswap_swap_in_total").get()
    by0 = obs.REGISTRY.get("gllm_kvswap_transfer_bytes_total").get(
        dir="out")
    llm = _make_llm("int8", num_pages=17, host_pages=64,
                    max_prefill_tokens=96)
    assert llm.swap_manager is not None
    got = _gen(llm, prompts, mk())
    pre = obs.REGISTRY.get("gllm_sched_preemptions_total").get() - pre0
    sin = obs.REGISTRY.get("gllm_kvswap_swap_in_total").get() - in0
    assert pre > 0, "no memory pressure — the test lost its teeth"
    assert sin == pre
    assert got == want
    # transfer-bytes counter reflects the narrow dtype: an int8 page is
    # cache-payload/2 + scale rows, and the host pool prices it that way
    by = obs.REGISTRY.get("gllm_kvswap_transfer_bytes_total").get(
        dir="out") - by0
    assert by > 0
    per_page = llm.swap_manager.pool.bytes_per_page
    L, ps = 2, 4
    hkv, d = 2, 16
    assert per_page == 2 * L * ps * hkv * d + 2 * L * hkv * 4
    assert by % per_page == 0


def test_int8_prefix_spill_restore_canary_verified():
    """Host-tier prefix spill/restore with an int8 cache: re-minted
    prefix pages spill payload+scales, and the canary-verified restore
    reproduces the uninterrupted continuation."""
    from gllm_tpu.obs import metrics as obs
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, 500, size=40).tolist()
    sp = lambda: [SamplingParams(temperature=0.0, max_tokens=8,  # noqa
                                 ignore_eos=True)]
    ref = _make_llm("int8", num_pages=128, prefix=True)
    want = ref.generate(prompt_token_ids=[list(prompt)],
                        sampling_params=sp())[0].output_token_ids

    llm = _make_llm("int8", num_pages=40, host_pages=128, prefix=True)
    got1 = llm.generate(prompt_token_ids=[list(prompt)],
                        sampling_params=sp())[0].output_token_ids
    assert got1 == want
    spill0 = obs.REGISTRY.get(
        "gllm_kvswap_prefix_spill_pages_total").get()
    for _ in range(6):
        filler = rng.integers(1, 500, size=60).tolist()
        llm.generate(prompt_token_ids=[filler], sampling_params=sp())
    assert obs.REGISTRY.get(
        "gllm_kvswap_prefix_spill_pages_total").get() > spill0
    rest0 = obs.REGISTRY.get(
        "gllm_kvswap_prefix_restore_pages_total").get()
    got2 = llm.generate(prompt_token_ids=[list(prompt)],
                        sampling_params=sp())[0].output_token_ids
    assert obs.REGISTRY.get(
        "gllm_kvswap_prefix_restore_pages_total").get() > rest0, \
        "prompt replay never hit the host tier"
    assert got2 == want


# ---- explicit gating ------------------------------------------------------

def test_config_rejects_unknown_kv_dtype():
    cfg = EngineConfig(cache=CacheConfig(kv_cache_dtype="int4"))
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        cfg.validate()
    EngineConfig(cache=CacheConfig(kv_cache_dtype="int8")).validate()


def _gated_runner(model_cfg):
    from gllm_tpu.runner.runner import ModelRunner
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=128,
        cache=CacheConfig(page_size=4, num_pages=32,
                          kv_cache_dtype="int8"))
    return ModelRunner(cfg, model_cfg)


def test_int8_gated_for_mla():
    mla = ModelConfig(architecture="DeepseekV2ForCausalLM",
                      vocab_size=128, hidden_size=64, num_layers=2,
                      num_heads=4, num_kv_heads=4, head_dim=16,
                      intermediate_size=96, kv_lora_rank=32,
                      qk_nope_head_dim=16, qk_rope_head_dim=8,
                      v_head_dim=16)
    with pytest.raises(NotImplementedError, match="MLA"):
        _gated_runner(mla)


def test_int8_gated_for_hybrid():
    hyb = ModelConfig(architecture="Qwen3NextForCausalLM",
                      vocab_size=128, hidden_size=64, num_layers=2,
                      num_heads=4, num_kv_heads=2, head_dim=16,
                      intermediate_size=96,
                      layer_types=("linear_attention", "full_attention"),
                      linear_num_value_heads=4, linear_num_key_heads=2,
                      linear_key_head_dim=8, linear_value_head_dim=8)
    with pytest.raises(NotImplementedError, match="hybrid"):
        _gated_runner(hyb)
