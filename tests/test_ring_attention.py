"""Ring attention (context parallelism) vs dense causal attention."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from gllm_tpu.parallel.ring_attention import ring_attention_sharded


def dense_causal(q, k, v, scale):
    T, Hq, D = q.shape
    Hkv = k.shape[1]
    group = Hq // Hkv
    out = np.zeros((T, Hq, v.shape[-1]), np.float32)
    for h in range(Hq):
        s = q[:, h] @ k[:, h // group].T * scale
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask, s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[:, h] = p @ v[:, h // group]
    return out


@pytest.mark.parametrize("sp,Hq,Hkv,T,D", [
    (4, 4, 2, 64, 32),
    (8, 8, 8, 64, 16),
    (2, 2, 1, 32, 64),
])
def test_ring_matches_dense(sp, Hq, Hkv, T, D):
    rng = np.random.default_rng(0)
    q = rng.standard_normal((T, Hq, D)).astype(np.float32)
    k = rng.standard_normal((T, Hkv, D)).astype(np.float32)
    v = rng.standard_normal((T, Hkv, D)).astype(np.float32)
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))
    got = ring_attention_sharded(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), mesh)
    want = dense_causal(q, k, v, D ** -0.5)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_ring_long_context_stability():
    # longer sequence + larger magnitudes: exercises the LSE merge across
    # all 8 hops
    rng = np.random.default_rng(1)
    T, Hq, Hkv, D = 256, 4, 2, 32
    q = (rng.standard_normal((T, Hq, D)) * 3).astype(np.float32)
    k = (rng.standard_normal((T, Hkv, D)) * 3).astype(np.float32)
    v = rng.standard_normal((T, Hkv, D)).astype(np.float32)
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("sp",))
    got = np.asarray(ring_attention_sharded(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh))
    want = dense_causal(q, k, v, D ** -0.5)
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-4)
    assert not np.isnan(got).any()
