"""Unified mixed-batch step (--unified-step, ISSUE 12).

Three layers under test (docs/overlap_scheduling.md#unified-step):

- KERNEL: the unified ragged kernel (``unified=True``) is the single
  attention program for every paged step — interpret-mode parity against
  BOTH legacy oracles (the per-sequence decode kernel for pure-decode
  batches, the XLA gather reference everywhere), f32 and int8 KV,
  including the AMLA mul-by-add rescaling numerics bounds.
- RUNNER/PREPARE: the shape-signature space collapses to one
  (row bucket × token bucket) family — max_q rides the token bucket,
  pure decode is the t == s point, mixed batches pad to the single
  schedulable maximum.
- ENGINE: chains absorb prefill chunks through mixed re-forms; greedy +
  seeded token streams are byte-identical to the flag-off engine under
  arrival/finish/preemption churn, and the retired
  ``reason="waiting"`` break class stays at zero.
"""

import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                             SchedulerConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.obs.steptrace import TRACE, summarize
from gllm_tpu.ops.attention import AttentionMetadata, _xla_paged_attention
from gllm_tpu.ops.pallas.decode_attention import paged_decode_attention
from gllm_tpu.ops.pallas.ragged_attention import (_decode_prefix_len,
                                                  ragged_paged_attention)
from gllm_tpu.sampling_params import SamplingParams


# ---------------------------------------------------------------------------
# kernel parity (interpret mode)
# ---------------------------------------------------------------------------

def build_case(rng, seqs, Hq, Hkv, D, page, num_pages, pad_seqs=0,
               int8=False):
    """seqs: list of (q_len, kv_len); decode rows must come first to
    mirror the scheduler's packing (the decode-prefix contract)."""
    S = len(seqs) + pad_seqs
    T = sum(q for q, _ in seqs)
    if int8:
        kc = rng.integers(-127, 127,
                          size=(num_pages, page, Hkv, D)).astype(np.int8)
        vc = rng.integers(-127, 127,
                          size=(num_pages, page, Hkv, D)).astype(np.int8)
        ks = rng.uniform(0.01, 0.02,
                         size=(num_pages, Hkv)).astype(np.float32)
        vs = rng.uniform(0.01, 0.02,
                         size=(num_pages, Hkv)).astype(np.float32)
    else:
        kc = rng.standard_normal((num_pages, page, Hkv, D)).astype(
            np.float32)
        vc = rng.standard_normal((num_pages, page, Hkv, D)).astype(
            np.float32)
        ks = vs = None
    max_pages = max(-(-kv // page) for _, kv in seqs)
    pt = np.zeros((S, max_pages), np.int32)
    cu = np.zeros(S + 1, np.int32)
    kv_lens = np.zeros(S, np.int32)
    nxt, off = 1, 0
    for i, (q_len, kv_len) in enumerate(seqs):
        n = -(-kv_len // page)
        pt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
        kv_lens[i] = kv_len
        off += q_len
        cu[i + 1] = off
    cu[len(seqs) + 1:] = off
    assert nxt <= num_pages
    q = rng.standard_normal((T, Hq, D)).astype(np.float32)
    md = AttentionMetadata(
        cu_q_lens=jnp.asarray(cu), kv_lens=jnp.asarray(kv_lens),
        page_table=jnp.asarray(pt),
        num_seqs=jnp.asarray(len(seqs), jnp.int32))
    return q, kc, vc, ks, vs, md


DECODE_SEQS = [(1, k) for k in [3, 9, 1, 14, 6, 2, 30, 8, 12, 5, 22, 17]]
MIXED_SEQS = [(1, k) for k in [3, 9, 14, 6, 30, 8]] + [(5, 9), (7, 7)]


@pytest.mark.parametrize("gsz", [1, 3, 4, 8])
def test_unified_pure_decode_matches_both_oracles(gsz):
    """Pure-decode ragged batch through the unified kernel == the legacy
    per-sequence decode kernel == the XLA reference — the decode-class
    grouped path at several interleave depths incl. partial groups."""
    rng = np.random.default_rng(7)
    q, kc, vc, _, _, md = build_case(rng, DECODE_SEQS, 8, 2, 32, 4, 96)
    scale = 0.2
    want = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kc),
                                jnp.asarray(vc), md, scale=scale,
                                max_q_len=1)
    oracle = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.kv_lens,
        md.page_table, scale=scale, kv_block=16, interpret=True)
    got = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=scale, q_block=8, kv_block=16,
        interpret=True, unified=True, group_size=gsz)
    np.testing.assert_allclose(np.asarray(oracle), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # AMLA quantizes the running max (exact power-of-two rescales): the
    # result is the same softmax computed with a different — exact —
    # normalizer split, so parity is tight but not bitwise
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)
    assert not np.isnan(np.asarray(got)).any()


def test_unified_mixed_matches_ragged_and_xla_oracles():
    rng = np.random.default_rng(3)
    q, kc, vc, _, _, md = build_case(rng, MIXED_SEQS, 8, 2, 32, 4, 64)
    scale = 0.2
    want = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kc),
                                jnp.asarray(vc), md, scale=scale,
                                max_q_len=7)
    legacy = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=scale, q_block=8, kv_block=16,
        interpret=True)
    got = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=scale, q_block=8, kv_block=16,
        interpret=True, unified=True, group_size=4)
    np.testing.assert_allclose(np.asarray(legacy), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("seqs", [DECODE_SEQS, MIXED_SEQS])
def test_unified_int8_kv_matches_xla_dequant_oracle(seqs):
    """int8 KV through the unified kernel (scale rows riding the page
    DMAs, in-VMEM dequant) vs the XLA gathered-page dequant oracle —
    decode-class and ragged-class blocks both."""
    rng = np.random.default_rng(5)
    q, kc, vc, ks, vs, md = build_case(rng, seqs, 8, 2, 32, 4, 96,
                                       int8=True)
    scale = 0.2
    max_q = max(ql for ql, _ in seqs)
    want = _xla_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md,
        k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs), scale=scale,
        max_q_len=max_q)
    got = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=scale, q_block=8, kv_block=16,
        interpret=True, unified=True, group_size=3,
        k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_amla_rescaling_numerics_bounds():
    """AMLA on vs off on the same unified batch: both must sit within
    oracle tolerance, and the classic (amla=False) arm must match the
    XLA oracle at the legacy tolerance — the mul-by-add trick changes
    only the normalizer split, never the math."""
    rng = np.random.default_rng(11)
    # wide score dynamic range: big scale stresses the exponent-field
    # rescale (underflow flush, -inf first blocks)
    q, kc, vc, _, _, md = build_case(rng, MIXED_SEQS, 4, 2, 32, 4, 64)
    scale = 1.7
    want = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kc),
                                jnp.asarray(vc), md, scale=scale,
                                max_q_len=7)
    classic = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=scale, q_block=8, kv_block=8,
        interpret=True, unified=True, group_size=2, amla=False)
    amla = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=scale, q_block=8, kv_block=8,
        interpret=True, unified=True, group_size=2, amla=True)
    np.testing.assert_allclose(np.asarray(classic), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(amla), np.asarray(want),
                               rtol=2e-3, atol=2e-3)
    assert not np.isnan(np.asarray(amla)).any()


def test_unified_mqa_and_padded_tail():
    """MQA (Hkv == 1, squeezed-head 2-D path) decode-class blocks +
    padded seq rows beyond the real batch."""
    rng = np.random.default_rng(13)
    seqs = [(1, 5), (1, 9), (1, 13), (6, 6)]
    q, kc, vc, _, _, md = build_case(rng, seqs, 4, 1, 64, 4, 16,
                                     pad_seqs=3)
    want = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kc),
                                jnp.asarray(vc), md, scale=0.2,
                                max_q_len=6)
    got = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=0.2, q_block=4, kv_block=8,
        interpret=True, unified=True, group_size=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_decode_prefix_len_derivation():
    """The per-block row class derives from cu_q_lens alone: the decode
    prefix is the longest run of one-token sequences."""
    cu = jnp.asarray([0, 1, 2, 3, 8, 9, 9, 9], jnp.int32)  # 3 decode,
    assert int(_decode_prefix_len(cu, 7)) == 3              # then a chunk
    cu = jnp.asarray([0, 1, 2, 3, 4, 4, 4], jnp.int32)     # pure decode
    assert int(_decode_prefix_len(cu, 6)) == 4              # (+ padding)
    cu = jnp.asarray([0, 5, 6, 7], jnp.int32)               # prefill first
    assert int(_decode_prefix_len(cu, 3)) == 0


# ---------------------------------------------------------------------------
# prepare: one signature family
# ---------------------------------------------------------------------------

def _builder(unified):
    from gllm_tpu.runner.prepare import BatchBuilder
    cfg = EngineConfig(max_num_seqs=32, unified_step=unified,
                       scheduler=SchedulerConfig(max_prefill_tokens=128,
                                                 max_decode_seqs=16),
                       cache=CacheConfig(page_size=4, num_pages=64))
    return BatchBuilder(cfg, 4, vocab_size=128)


def _sched_batch(rows):
    """rows: list of (q_len, computed_before)."""
    from gllm_tpu.scheduler import ScheduledBatch, ScheduledSeq
    from gllm_tpu.sequence import Sequence
    items = []
    for i, (n, before) in enumerate(rows):
        seq = Sequence(i, [1] * (before + n + 1), SamplingParams())
        seq.page_table = [1] * (-(-(before + n) // 4))
        seq.num_computed_tokens = before
        items.append(ScheduledSeq(seq, n, before))
    return ScheduledBatch(items)


def test_signature_collapses_to_one_family():
    b = _builder(True)
    # pure decode: the t == s point of the q == t family
    t, s, q, p = b.shape_signature(_sched_batch([(1, 6)] * 6))
    assert (t, s, q) == (8, 8, 8)
    # mixed: token axis pads to the ONE schedulable maximum
    t2, s2, q2, _ = b.shape_signature(_sched_batch([(1, 6)] * 6
                                                   + [(20, 0)]))
    assert q2 == t2 == b.max_tokens
    assert s2 == 8
    # legacy split for contrast: a q=1 decode population of its own
    lb = _builder(False)
    _, _, q3, _ = lb.shape_signature(_sched_batch([(1, 6)] * 6))
    assert q3 == 1


# ---------------------------------------------------------------------------
# engine: absorb, identity, retired break class
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_cfg():
    return ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=512, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, max_position=256)


def make_llm(model_cfg, *, unified, overlap=True, num_pages=256,
             eos=(7,), depth=2, **kw):
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=64,
        max_num_seqs=8, overlap_scheduling=overlap,
        unified_step=unified, overlap_depth=depth,
        pipelined_loop=(overlap and not unified),  # unified lifts it
        scheduler=SchedulerConfig(max_prefill_tokens=32,
                                  max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=num_pages), **kw)
    llm = LLM(config=cfg, model_cfg=model_cfg)
    if eos:
        llm.eos_token_ids = frozenset(eos)
    return llm


def check_no_leak(llm):
    assert llm.memory_manager.num_free_pages == \
        llm.memory_manager.allocator.num_total


def churn_run(model_cfg, unified, *, seeded=False, msd=1, slots=False,
              num_pages=256, n=10, depth=2, topo=None):
    """Arrivals land MID-CHAIN (the phase-boundary edge the unified step
    absorbs); optional page pressure exercises the no-preempt re-form
    fallback."""
    llm = make_llm(model_cfg, unified=unified, num_pages=num_pages,
                   multi_step_decode=msd, decode_slot_batching=slots,
                   ondevice_finish=slots, depth=depth,
                   parallel=ParallelConfig(**(topo or {})))
    rng = np.random.default_rng(11)
    seqs, nseq, it = [], 0, 0
    arrivals = {0: 3, 2: 2, 5: 2, 9: 1, 14: 2}
    while nseq < n or llm.has_unfinished:
        for _ in range(arrivals.get(it, 0)):
            if nseq >= n:
                break
            ids = [int(x) for x in
                   rng.integers(2, 250, size=int(rng.integers(3, 20)))]
            sp = (SamplingParams(temperature=0.8, seed=100 + nseq,
                                 max_tokens=int(rng.integers(4, 24)))
                  if seeded else
                  SamplingParams(temperature=0.0,
                                 max_tokens=int(rng.integers(4, 24))))
            s = llm._allocate_seq(ids, sp)
            seqs.append(s)
            llm.add_seq(s)
            nseq += 1
        llm.step()
        it += 1
        assert it < 3000, "engine stopped making progress"
    check_no_leak(llm)
    assert not llm._in_flight
    return [(s.token_ids[:], s.finish_reason) for s in seqs], llm


@pytest.mark.parametrize("kw", [
    {},                                     # arrivals only
    {"seeded": True},                       # seeded draws
    {"msd": 4, "slots": True},              # fused + slots + odf
    {"num_pages": 24},                      # + preemption pressure
    {"num_pages": 24, "msd": 4},            # fused + preemption
])
def test_unified_matches_legacy_under_churn(model_cfg, kw):
    base, _ = churn_run(model_cfg, False, **kw)
    uni, llm = churn_run(model_cfg, True, **kw)
    assert base == uni
    if kw.get("num_pages"):
        assert llm.scheduler.num_preemptions > 0


@pytest.mark.slow       # fresh engine per arm × 6 rows — tier-1 keeps the
                        # topology identity core in test_fast_path_topology.py
@pytest.mark.parametrize("topo,kw", [
    (dict(pp=2), {}),
    (dict(pp=2), dict(slots=True)),      # slot membership rides pp
    (dict(dp=2), {}),
], ids=["pp2", "pp2-slots", "dp2"])
@pytest.mark.parametrize("seeded", [False, True],
                         ids=["greedy", "seeded"])
def test_unified_matches_legacy_under_churn_multi_device(
        model_cfg, multi_device_cpu, topo, kw, seeded):
    """The churn identity matrix over topology (ISSUE 20): at pp=2 and
    dp=2 on the forced multi-device CPU host the unified dispatch family
    commits the same streams as the split family — both arms ride the
    lifted pipelined loop, so this also pins reform-chaining across
    stages / replicas against the per-topology legacy dispatch."""
    base, _ = churn_run(model_cfg, False, seeded=seeded, topo=topo, **kw)
    uni, _ = churn_run(model_cfg, True, seeded=seeded, topo=topo, **kw)
    assert base == uni


def test_unified_zero_waiting_breaks_and_mixed_steps(model_cfg):
    """The retired break class stays at zero while arrivals land
    mid-chain, every collected step records the unified kind, and mixed
    unified steps (chains absorbing prefill) actually happen."""
    mark = TRACE.mark()
    _, _ = churn_run(model_cfg, True, msd=4, slots=True)
    s = summarize(TRACE.events(since=mark))
    assert s["chain_breaks_by_reason"].get("waiting", 0) == 0
    step_kinds = set(s["by_kind"]) - {"fused_block"}
    assert step_kinds == {"unified_step"}, s["by_kind"]
    assert s["mixed_step_frac"] and s["mixed_step_frac"] > 0
    # legacy control on the same workload DOES hit the waiting class —
    # the absorb path is load-bearing, not vacuously green
    mark = TRACE.mark()
    churn_run(model_cfg, False, msd=4, slots=True)
    s2 = summarize(TRACE.events(since=mark))
    assert s2["chain_breaks_by_reason"].get("waiting", 0) > 0
    assert s2["mixed_step_frac"] is None


def test_unified_sync_loop_byte_identical(model_cfg):
    """--unified-step without overlap scheduling: signature collapse +
    kernel routing only — streams byte-identical to legacy sync."""
    rng = np.random.default_rng(3)
    prompts = [[int(x) for x in rng.integers(2, 500, size=int(m))]
               for m in rng.integers(3, 14, size=5)]
    sps = [SamplingParams(temperature=0.0, max_tokens=int(m),
                          ignore_eos=True)
           for m in rng.integers(4, 16, size=5)]

    def run(unified):
        llm = make_llm(model_cfg, unified=unified, overlap=False, eos=())
        outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                            sampling_params=sps)
        check_no_leak(llm)
        return [(o.output_token_ids, o.finish_reason) for o in outs]

    assert run(False) == run(True)


def test_unified_reform_splices_and_absorbs(model_cfg):
    """Structural: under pressure the unified loop dispatches MIXED
    re-formed batches (src_rows with both promised decode rows and
    host-known prefill rows) instead of yielding."""
    llm = make_llm(model_cfg, unified=True, multi_step_decode=4,
                   decode_slot_batching=True, ondevice_finish=True)
    mixed_reforms = []
    orig = llm.scheduler.schedule_reform

    def spy(prev, allow_prefill=False):
        out = orig(prev, allow_prefill=allow_prefill)
        if out is not None and any(
                it.num_new_tokens > 1
                or it.computed_before < it.seq.prompt_len
                for it in out.items):
            mixed_reforms.append(out)
        return out

    llm.scheduler.schedule_reform = spy
    rng = np.random.default_rng(11)
    nseq, it = 0, 0
    arrivals = {0: 3, 4: 2, 8: 2}
    while nseq < 7 or llm.has_unfinished:
        for _ in range(arrivals.get(it, 0)):
            ids = [int(x) for x in
                   rng.integers(2, 250, size=int(rng.integers(6, 20)))]
            llm.add_seq(llm._allocate_seq(
                ids, SamplingParams(temperature=0.0, max_tokens=12,
                                    ignore_eos=True)))
            nseq += 1
        llm.step()
        it += 1
        assert it < 2000
    check_no_leak(llm)
    assert mixed_reforms, "no chain absorbed a prefill chunk"
    # at least one mixed re-form carries BOTH a promised decode row
    # (spliced from the previous entry's on-device tokens) and a
    # host-known prefill row — the chain absorbing an arrival
    absorbing = [b for b in mixed_reforms
                 if b.src_rows is not None
                 and any(src >= 0 for src in b.src_rows)
                 and any(src < 0 for src in b.src_rows)]
    assert absorbing, "no mixed re-form carried promised decode rows " \
                      "next to prefill rows"
    for b in mixed_reforms:
        # decode prefix first: the kernel's row-class contract
        qlens = [it.num_new_tokens for it in b.items]
        first_chunk = next((i for i, it in enumerate(b.items)
                            if it.num_new_tokens > 1
                            or it.computed_before < it.seq.prompt_len),
                           len(qlens))
        assert all(n == 1 for n in qlens[:first_chunk])


def test_dispatch_shape_acceptance(model_cfg):
    """Acceptance (ISSUE 12): on a staggered-arrival churn workload the
    unified step warms STRICTLY fewer distinct dispatch signatures than
    the split engine (one family vs the decode+mixed populations and
    their token ladder), runs no more unfused decode steps, and retires
    the 'waiting' break class — all deterministic counts, not wall
    fractions (the wall-based unfused_frac is already ≈0 in both arms
    since the pipelined loop landed; bench.py's unified_ab reports
    both)."""
    def arm(unified):
        llm = make_llm(model_cfg, unified=unified, multi_step_decode=4,
                       decode_slot_batching=True, ondevice_finish=True,
                       chain_under_prefill=0 if unified else 4)
        rng = np.random.default_rng(7)
        nseq, it = 0, 0
        arrivals = {0: 3, 2: 2, 5: 2, 9: 1, 14: 2}
        mark = TRACE.mark()
        while nseq < 10 or llm.has_unfinished:
            for _ in range(arrivals.get(it, 0)):
                if nseq >= 10:
                    break
                ids = [int(x) for x in
                       rng.integers(2, 250,
                                    size=int(rng.integers(3, 20)))]
                llm.add_seq(llm._allocate_seq(
                    ids, SamplingParams(temperature=0.0, ignore_eos=True,
                                        max_tokens=int(
                                            rng.integers(4, 24)))))
                nseq += 1
            llm.step()
            it += 1
            assert it < 3000
        s = summarize(TRACE.events(since=mark))
        return (llm.runner.num_shape_signatures,
                s["decode_steps_unfused"],
                s["chain_breaks_by_reason"])

    sigs_on, unfused_on, breaks_on = arm(True)
    sigs_off, unfused_off, breaks_off = arm(False)
    assert sigs_on < sigs_off, (sigs_on, sigs_off)
    assert unfused_on <= unfused_off, (unfused_on, unfused_off)
    assert breaks_on.get("waiting", 0) == 0


def test_inflight_depth_knob(model_cfg):
    """--inflight-depth is a real knob: at depth 3 the pipelined loop
    sustains a strictly deeper run-ahead than at the default 2 on a
    decode-saturated workload."""
    def mean_depth(depth):
        llm = make_llm(model_cfg, unified=True, depth=depth, eos=())
        rng = np.random.default_rng(5)
        prompts = [[int(x) for x in rng.integers(2, 500, size=6)]
                   for _ in range(6)]
        sps = [SamplingParams(temperature=0.0, max_tokens=40,
                              ignore_eos=True) for _ in range(6)]
        llm.generate(prompt_token_ids=prompts, sampling_params=sps)
        mark = TRACE.mark()
        llm.generate(prompt_token_ids=prompts, sampling_params=sps)
        return summarize(TRACE.events(since=mark))["mean_inflight_depth"]

    d2, d3 = mean_depth(2), mean_depth(3)
    assert d3 > d2, (d2, d3)
    assert d3 > 1.0, d3


def test_config_deprecates_chain_under_prefill():
    import logging
    cfg = EngineConfig(overlap_scheduling=True, unified_step=True,
                       chain_under_prefill=8)
    with warnings.catch_warnings():
        logging.disable(logging.NOTSET)
        cfg.validate()
    assert cfg.chain_under_prefill == 0          # deprecated no-op
    assert cfg.pipelined_loop                    # lifted under overlap


def test_config_unified_without_overlap_stays_sync():
    cfg = EngineConfig(unified_step=True)
    cfg.validate()
    assert not cfg.pipelined_loop and not cfg.overlap_scheduling


def test_config_rejects_bad_inflight_depth():
    cfg = EngineConfig(overlap_depth=0)
    with pytest.raises(ValueError, match="inflight-depth"):
        cfg.validate()
