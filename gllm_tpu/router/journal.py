"""Router-side stream journal (docs/robustness.md#fleet-topology--failover).

Mirrors the in-process ``engine/recovery.RequestJournal`` contract one
level up: per proxied stream, the IMMUTABLE submission (the client's
request body + the prompt token ids the first replica reported) plus the
output token ids actually FORWARDED to the client. Forwarded = committed:
a token the dead replica generated but the router never relayed is not
committed and will be regenerated identically by the continuation; a
token the router relayed is committed and is never regenerated — zero
lost, zero duplicated tokens across a failover.

The safety predicate is split across the two planes that each know half
of it:

- :func:`router_unsafe_reason` vetoes what only the router can see in
  the request body — multi-choice streams (``n``/``best_of`` > 1
  interleave by index and cannot be re-spliced) and tool-call streaming
  (structured deltas must not re-emit);
- the replica's preamble event carries ``unsafe_reason`` computed by the
  PR 14 :class:`~gllm_tpu.engine.recovery.JournalEntry` predicate
  (greedy or seeded only, no mm / disagg / stop strings /
  prompt_logprobs), which needs the tokenized prompt and parsed
  sampling params only the replica has.

Either veto ⇒ the stream never fails over once content was delivered;
it ends with a terminal error chunk carrying ``retry_after`` instead.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional


def router_unsafe_reason(body: dict, kind: str) -> Optional[str]:
    """The router-side half of the replay-safety predicate — vetoes the
    request shapes whose SSE streams cannot be resumed by resubmitting
    prompt + committed ids. None = no router-side veto (the replica
    preamble may still veto on sampling/mm grounds)."""
    try:
        n = int(body.get("n") or 1)
        best_of = int(body.get("best_of") or n)
    except (TypeError, ValueError):
        return "malformed n/best_of"
    if n != 1 or best_of != 1:
        return "multi-choice streams interleave by index"
    if kind == "chat" and body.get("tools") \
            and body.get("tool_choice") != "none":
        return "tool-call streams may not re-emit structured deltas"
    return None


@dataclasses.dataclass
class StreamEntry:
    """One proxied stream's journal record."""

    rid: str                              # router-owned request id
    kind: str                             # "chat" | "completion"
    body: dict                            # client body, verbatim
    session: Optional[str] = None         # affinity key, if any
    # None until the replica preamble arrives (or a router-side veto
    # set it at open); non-None vetoes mid-stream failover
    unsafe_reason: Optional[str] = None
    prompt_token_ids: Optional[List[int]] = None
    committed: List[int] = dataclasses.field(default_factory=list)
    committed_text_len: int = 0           # chars forwarded (diagnostics)
    delivered_events: int = 0             # SSE events forwarded
    finished: bool = False
    finish_reason: Optional[str] = None
    replica: Optional[str] = None         # current upstream address
    replica_identity: Optional[tuple] = None
    attempts: int = 0                     # upstream attempts so far
    migration_attempts: int = 0           # failures AFTER delivery began
    failovers: int = 0                    # successful migrations
    opened_at: float = dataclasses.field(default_factory=time.monotonic)
    # failover timing: detection → first continuation event forwarded
    fail_detected_at: Optional[float] = None
    last_failover_s: Optional[float] = None
    # pd-pool handoff (docs/pd_pools.md): the decode replica picked at
    # dispatch (its prefix serve addr got the KV push), whether the
    # stream already migrated pools, how many pages the decode side
    # accepted, and when the handoff was raised (timing histogram)
    pd_target: Optional[str] = None
    pd_migrated: bool = False
    pushed_pages: int = 0
    pd_handoff_at: Optional[float] = None

    @property
    def replay_safe(self) -> bool:
        return self.unsafe_reason is None

    @property
    def can_restart(self) -> bool:
        """A stream that delivered NOTHING yet can always restart from
        scratch on another replica — determinism only matters once the
        client holds part of the answer."""
        return self.delivered_events == 0

    def continuation_payload(self) -> Optional[dict]:
        """The ``gllm_router.continuation`` object for a resubmission,
        or None when the stream must restart from scratch (nothing
        delivered yet — the fresh submit path re-encodes)."""
        if self.delivered_events == 0 or self.prompt_token_ids is None:
            return None
        return {"prompt_token_ids": list(self.prompt_token_ids),
                "committed_token_ids": list(self.committed)}


class StreamJournal:
    """Thread-safe registry of the streams currently in flight through
    the router (each HTTP handler thread owns one entry; the health
    poller reads the registry for restart-triggered failover and
    /router_info)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, StreamEntry] = {}

    def open(self, entry: StreamEntry) -> StreamEntry:
        with self._lock:
            self._entries[entry.rid] = entry
        return entry

    def close(self, rid: str) -> Optional[StreamEntry]:
        with self._lock:
            return self._entries.pop(rid, None)

    def active(self) -> List[StreamEntry]:
        with self._lock:
            return list(self._entries.values())

    def by_replica(self, addr: str) -> List[StreamEntry]:
        with self._lock:
            return [e for e in self._entries.values()
                    if e.replica == addr]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
