"""Small shared helpers (shape bucketing, math, circuit breaking).

The bucketing helpers implement the static-shape discipline XLA wants: every
jit-compiled step function sees only a small set of padded shapes, mirroring the
reference engine's power-of-two CUDA-graph buckets
(/root/reference/gllm/model_runner.py:471-489).

:class:`CircuitBreaker` is the shared per-remote failure ladder: the
prefix-peer client (kvstore/peer.py) and the fleet front router
(gllm_tpu/router/) both talk to remotes that can die, flap, or
crash-loop, and both need the same guarantee — a broken remote costs at
most one probe per backoff window, never a per-request stall.
"""

from __future__ import annotations

import os
import time
from typing import Optional


class CircuitBreaker:
    """Per-remote circuit breaker (docs/robustness.md#peer-breakers).

    closed → (``threshold`` consecutive failures) → open for
    ``base_s · 2^(trips-1)`` seconds ±``jitter`` (capped at ``max_s``)
    → half-open: exactly ONE probe is admitted — success closes and
    resets the backoff ladder, failure re-opens with the next-longer
    window. The jitter de-synchronizes a fleet of replicas hammering
    the same recovering remote.

    Single-threaded by contract (one prober owns each instance —
    the engine thread for prefix peers, the router's health poller for
    serving replicas); ``now`` injection keeps the chaos tests
    clock-free.
    """

    def __init__(self, base_s: float = 30.0, max_s: float = 300.0,
                 threshold: int = 1, jitter: float = 0.1):
        self.base_s = max(0.001, float(base_s))
        self.max_s = max(self.base_s, float(max_s))
        self.threshold = max(1, int(threshold))
        self.jitter = max(0.0, min(1.0, float(jitter)))
        self.state = "closed"            # closed | open | half_open
        self.trips = 0                   # consecutive opens (backoff rung)
        self._fails = 0                  # consecutive failures while closed
        self._until = 0.0                # open-state expiry (monotonic)
        # lifetime health counters (surfaced on /server_info and
        # /router_info)
        self.failures = 0
        self.successes = 0
        self.opens = 0
        self.probes = 0                  # half-open recovery probes

    def allow(self, now: Optional[float] = None) -> bool:
        """May the caller probe this remote now? The True returned after
        an open window expires IS the single half-open probe — further
        calls return False until success()/failure() resolves it."""
        if self.state == "closed":
            return True
        if self.state == "half_open":
            return False
        now = time.monotonic() if now is None else now
        if now >= self._until:
            self.state = "half_open"
            self.probes += 1
            return True
        return False

    def success(self) -> None:
        self.successes += 1
        self.state = "closed"
        self._fails = 0
        self.trips = 0

    def failure(self, now: Optional[float] = None) -> None:
        self.failures += 1
        if self.state == "half_open":
            self._open(now)              # the recovery probe failed
            return
        if self.state == "open":
            return                       # already backing off
        self._fails += 1
        if self._fails >= self.threshold:
            self._open(now)

    def _open(self, now: Optional[float]) -> None:
        now = time.monotonic() if now is None else now
        self.trips += 1
        self._fails = 0
        self.opens += 1
        self.state = "open"
        back = min(self.max_s, self.base_s * (2 ** (self.trips - 1)))
        if self.jitter:
            import random
            back *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
        self._until = now + back

    def down_for(self, now: Optional[float] = None) -> float:
        if self.state != "open":
            return 0.0
        now = time.monotonic() if now is None else now
        return max(0.0, self._until - now)

    def health(self) -> dict:
        return {"state": self.state, "trips": self.trips,
                "failures": self.failures, "successes": self.successes,
                "opens": self.opens, "probes": self.probes,
                "down_for_s": round(self.down_for(), 2)}


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up(x: int, multiple: int) -> int:
    return cdiv(x, multiple) * multiple


def next_pow2(x: int, minimum: int = 1) -> int:
    """Smallest power of two >= max(x, minimum)."""
    v = max(x, minimum, 1)
    return 1 << (v - 1).bit_length()


def bucket_size(x: int, minimum: int, maximum: int) -> int:
    """Pad ``x`` to a power-of-two bucket, clamped to [minimum, maximum].

    Keeps the number of distinct compiled shapes logarithmic in the range —
    the XLA-compilation-cache analogue of the reference's CUDA-graph bucket
    table (/root/reference/gllm/model_runner.py:1525-1615).
    """
    if x > maximum:
        raise ValueError(f"size {x} exceeds maximum bucket {maximum}")
    return min(next_pow2(x, minimum), maximum)


class LRUBytesCache:
    """Byte-budgeted LRU (reference MultiModalEmbeddingCache,
    model_runner.py:161-221): caps both entry count and total bytes so one
    huge entry can't squat on the pool. Thread-safe: the multihost blob
    chain serves this cache from a peer-server handler thread while the
    engine thread writes it."""

    def __init__(self, max_entries: int = 64, max_mb: float = 256.0):
        import threading
        from collections import OrderedDict
        self._cache = OrderedDict()
        self._lock = threading.Lock()
        self.max_entries = max_entries
        self.max_bytes = int(max_mb * 1024 * 1024)
        self._cur_bytes = 0
        self.hits = 0
        self.misses = 0
        # Keys whose values exceeded max_bytes and were rejected by put():
        # a peer serving this cache can answer "will never have" instead
        # of letting downstream fetchers poll out their full deadline.
        self.oversize = set()
        self._oversize_capped = False

    @staticmethod
    def _size_of(value) -> int:
        nbytes = getattr(value, "nbytes", None)
        if nbytes is not None:
            return int(nbytes)
        if isinstance(value, (bytes, bytearray, memoryview)):
            return len(value)
        return 0

    def get(self, key):
        with self._lock:
            v = self._cache.get(key)
            if v is None:
                self.misses += 1
                return None
            self.hits += 1
            self._cache.move_to_end(key)
            return v

    def pop(self, key) -> None:
        """Invalidate one entry (a caller replaced or poisoned the
        underlying data; the cached copy must not be served again)."""
        with self._lock:
            v = self._cache.pop(key, None)
            if v is not None:
                self._cur_bytes -= self._size_of(v)

    def put(self, key, value) -> None:
        sz = self._size_of(value)
        if sz > self.max_bytes:
            with self._lock:
                if key not in self.oversize:
                    import logging
                    log = logging.getLogger("gllm_tpu")
                    if len(self.oversize) < 1024:
                        self.oversize.add(key)
                        log.warning(
                            "LRUBytesCache: value for %r (%d B) exceeds "
                            "max_bytes=%d — never cacheable", key, sz,
                            self.max_bytes)
                    elif not self._oversize_capped:
                        self._oversize_capped = True
                        log.warning(
                            "LRUBytesCache: oversize-key set capped at "
                            "1024 — further oversize keys lose the peer "
                            "'never' fast-path")
            return
        with self._lock:
            if key in self._cache:
                self._cur_bytes -= self._size_of(self._cache[key])
                self._cache.move_to_end(key)
            self._cache[key] = value
            self._cur_bytes += sz
            while (len(self._cache) > self.max_entries
                   or self._cur_bytes > self.max_bytes):
                _, evicted = self._cache.popitem(last=False)
                self._cur_bytes -= self._size_of(evicted)


def enable_compilation_cache(cache_dir: str = None) -> str:
    """Turn on JAX's persistent (on-disk) XLA compilation cache.

    Serving cold-start is compile-bound: the bucketed jit grid is ~15-30
    programs and a TPU compile through the remote tunnel costs tens of
    seconds each (the reference pays the analogous cost once per CUDA-graph
    capture, model_runner.py:1525). With the persistent cache every process
    that compiles the same (program, compile-options) pair — a restarted
    server, a bench retry after a tunnel wedge, the next round — reuses the
    serialized executable instead of recompiling.

    min_entry_size/min_compile_time are forced to 0 because the default
    thresholds (1 s compile floor) silently skip exactly the small bucketed
    decode programs we most need cached. Safe to call repeatedly; first
    caller's directory wins. Returns the directory in effect.
    """
    import jax
    d = (cache_dir
         or os.environ.get("GLLM_TPU_XLA_CACHE")
         or os.environ.get("JAX_COMPILATION_CACHE_DIR")
         or os.path.expanduser("~/.cache/gllm_tpu/xla_cache"))
    existing = jax.config.jax_compilation_cache_dir
    if existing:
        d = existing
    else:
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
    # Zero the skip thresholds, but only where they still hold jax's
    # library defaults (0 bytes / 1.0 s): a pre-existing non-default value
    # is a deliberate choice by an embedding application and is respected.
    # A cache DIR configured via env expresses no opinion on thresholds,
    # so the zeros still apply there.
    for knob, default in (("jax_persistent_cache_min_entry_size_bytes", 0),
                          ("jax_persistent_cache_min_compile_time_secs",
                           1.0)):
        try:
            if getattr(jax.config, knob) == default:
                jax.config.update(knob, 0)
        except Exception:  # pragma: no cover - knob renamed upstream
            pass
    return d


def tpu_compiler_options() -> dict:
    """Per-jit XLA compile options for the TPU backend.

    Scoped-VMEM limit: XLA's default 16 MiB scope can't hold a Pallas
    attention kernel's buffers plus an operand/result XLA chooses to stage
    in VMEM (observed on v5e: 19.3 MiB requested for the ragged kernel at
    the 1024-token prefill bucket). v5e cores carry 128 MiB of VMEM; 64 MiB
    leaves ample headroom. Passed via jit(compiler_options=...) because the
    bench host parses XLA_FLAGS with a CPU-only XLA (TPU flags are fatal
    there) and compiles TPU programs remotely."""
    import jax
    if jax.default_backend() in ("tpu", "axon"):
        return {"xla_tpu_scoped_vmem_limit_kib": 65536}
    return None
