"""GLM4: sandwich norms + partial interleaved rotary, HF oracle."""

import torch

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams


def test_glm4_greedy_equivalence(tmp_path):
    from transformers import Glm4Config, Glm4ForCausalLM
    torch.manual_seed(17)
    hf = Glm4ForCausalLM(Glm4Config(
        vocab_size=128, hidden_size=64, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        head_dim=16, partial_rotary_factor=0.5, attention_bias=True,
        max_position_embeddings=256, eos_token_id=0, pad_token_id=0,
        tie_word_embeddings=False))
    hf.eval()
    hf.save_pretrained(tmp_path, safe_serialization=True)

    cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                       max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=64))
    llm = LLM(config=cfg)
    prompts = [[7, 3, 56, 21], [99, 14, 2, 8, 30]]
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
    for p, out in zip(prompts, outs):
        ids = list(p)
        with torch.no_grad():
            for _ in range(8):
                ids.append(int(hf(torch.tensor([ids])).logits[0, -1]
                               .argmax()))
        assert out.output_token_ids == ids[len(p):], (p,
                                                      out.output_token_ids,
                                                      ids[len(p):])


def test_glm_base_greedy_equivalence(tmp_path):
    """GLM-4 base (GlmForCausalLM): interleaved partial rotary + fused
    gate_up + qkv bias, WITHOUT GLM4's sandwich norms."""
    from transformers import GlmConfig, GlmForCausalLM
    torch.manual_seed(17)
    hf = GlmForCausalLM(GlmConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=96, partial_rotary_factor=0.5,
        attention_bias=True, max_position_embeddings=256,
        eos_token_id=0, pad_token_id=0))
    hf.eval()
    hf.save_pretrained(tmp_path, safe_serialization=True)

    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams
    llm = LLM(config=EngineConfig(
        model=str(tmp_path), dtype="float32", max_model_len=128,
        cache=CacheConfig(page_size=4, num_pages=64)))
    prompts = [[5, 17, 93, 41], [9, 3, 77]]
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
    import torch as _t
    for p, o in zip(prompts, outs):
        ids = list(p)
        with _t.no_grad():
            for _ in range(8):
                logits = hf(_t.tensor([ids])).logits[0, -1]
                ids.append(int(logits.argmax()))
        assert o.output_token_ids == ids[len(p):], (p, o.output_token_ids)
