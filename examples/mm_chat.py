#!/usr/bin/env python
"""Multimodal chat against a running gllm_tpu api_server.

Role parity with the reference's examples/mm_chat.py (OpenAI client +
base64 image chat), stdlib-only: images are inlined as ``data:`` URLs in
OpenAI image_url content parts, which the server decodes through its MM
pipeline (ViT + token splicing).

  python -m gllm_tpu.entrypoints.api_server --model <qwen-vl-ckpt> &
  python examples/mm_chat.py --image cat.png "What is in this image?"

Without --image a tiny synthetic RGB gradient is sent (smoke mode — no
files needed)."""

import argparse
import base64
import io
import json
import struct
import urllib.request
import zlib


def synth_png(w=64, h=64):
    """Minimal in-process PNG writer (RGB gradient) — keeps the example
    runnable with zero assets."""
    raw = b""
    for y in range(h):
        row = b"\x00"
        for x in range(w):
            row += bytes((int(255 * x / w), int(255 * y / h), 128))
        raw += row

    def chunk(tag, data):
        c = struct.pack(">I", len(data)) + tag + data
        return c + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF)

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    return (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", zlib.compress(raw)) + chunk(b"IEND", b""))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prompt", nargs="?",
                    default="Describe this image in one sentence.")
    ap.add_argument("--image", help="image file (png/jpeg); synthetic "
                                    "gradient when omitted")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-tokens", type=int, default=128)
    args = ap.parse_args()

    if args.image:
        data = open(args.image, "rb").read()
        mime = ("image/jpeg" if args.image.lower().endswith((".jpg",
                                                             ".jpeg"))
                else "image/png")
    else:
        data, mime = synth_png(), "image/png"
    url = f"data:{mime};base64,{base64.b64encode(data).decode()}"

    body = {
        "model": "default",
        "max_tokens": args.max_tokens,
        "messages": [{
            "role": "user",
            "content": [
                {"type": "image_url", "image_url": {"url": url}},
                {"type": "text", "text": args.prompt},
            ],
        }],
    }
    req = urllib.request.Request(
        f"http://{args.host}:{args.port}/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        out = json.load(io.TextIOWrapper(r, "utf-8"))
    msg = out["choices"][0]["message"]
    print(msg.get("content", ""))
    print(f"[usage] {out.get('usage')}")


if __name__ == "__main__":
    main()
