"""Qwen3-VL (+MoE): HF-greedy equivalence through the full engine.

Deepstack coverage per SURVEY.md §2.3 (reference qwen3_vl.py /
qwen3_vl_moe.py): interpolated pos-embeds, per-frame ViT attention,
deepstack per-layer residual injection, interleaved mrope, per-frame video
spans, and the fused-expert MoE text backbone.
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams

IMG, VID, VSTART, VEND = 150, 151, 152, 153

TEXT = dict(
    vocab_size=160, hidden_size=64, num_hidden_layers=3,
    num_attention_heads=4, num_key_value_heads=2, head_dim=16,
    intermediate_size=96, max_position_embeddings=512, rms_norm_eps=1e-6,
    rope_theta=10000.0, tie_word_embeddings=False,
    rope_scaling={"rope_type": "default", "mrope_section": [2, 3, 3],
                  "mrope_interleaved": True},
)
VISION = dict(
    depth=3, hidden_size=32, intermediate_size=48, num_heads=4,
    patch_size=2, temporal_patch_size=2, in_channels=3,
    spatial_merge_size=2, out_hidden_size=64, num_position_embeddings=16,
    deepstack_visual_indexes=[0, 2], hidden_act="gelu_pytorch_tanh",
)


@pytest.fixture(scope="module")
def vl3_ckpt(tmp_path_factory):
    from transformers import (Qwen3VLConfig,
                              Qwen3VLForConditionalGeneration)
    torch.manual_seed(21)
    cfg = Qwen3VLConfig(
        text_config=TEXT, vision_config=VISION,
        image_token_id=IMG, video_token_id=VID,
        vision_start_token_id=VSTART, vision_end_token_id=VEND,
        eos_token_id=0, bos_token_id=1)
    model = Qwen3VLForConditionalGeneration(cfg)
    model.eval()
    d = tmp_path_factory.mktemp("tiny_vl3")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


def make_image(rng, grid=(1, 4, 4)):
    t, h, w = grid
    dim = 3 * 2 * 2 * 2
    pix = rng.standard_normal((t * h * w, dim)).astype(np.float32)
    n_tok = t * (h // 2) * (w // 2)
    return pix, np.asarray([list(grid)]), n_tok


def vl_prompt(pre, grid_toks, post, tok=IMG):
    return list(pre) + [VSTART] + [tok] * grid_toks + [VEND] + list(post)


def hf_greedy(model, ids, n, **mm):
    with torch.no_grad():
        out = model.generate(input_ids=torch.tensor([ids]),
                             max_new_tokens=n, do_sample=False, **mm)
    return out[0, len(ids):].tolist()


def make_llm(model_dir, prefix=False, **sched):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=256,
        scheduler=SchedulerConfig(**sched) if sched else SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=prefix))
    return LLM(config=cfg)


def test_vl3_greedy_equivalence(vl3_ckpt):
    model_dir, hf = vl3_ckpt
    rng = np.random.default_rng(0)
    pix, grid, n_tok = make_image(rng)
    ids = vl_prompt([5, 9, 23], n_tok, [7, 30, 41])
    want = hf_greedy(hf, ids, 8, pixel_values=torch.tensor(pix),
                     image_grid_thw=torch.tensor(grid))

    llm = make_llm(model_dir)
    got = llm.generate(
        prompt_token_ids=[ids],
        mm_inputs=[{"pixel_values": pix, "image_grid_thw": grid}],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))[0]
    assert got.output_token_ids == want, (got.output_token_ids, want)


def test_vl3_two_images_chunked_and_text_mix(vl3_ckpt):
    model_dir, hf = vl3_ckpt
    rng = np.random.default_rng(3)
    pix_a, grid_a, n_a = make_image(rng, (1, 4, 4))
    pix_b, grid_b, n_b = make_image(rng, (1, 4, 8))
    two_pix = np.concatenate([pix_a, pix_b])
    two_grid = np.concatenate([grid_a, grid_b])
    ids2 = (vl_prompt([5, 9], n_a, [12])
            + [VSTART] + [IMG] * n_b + [VEND] + [44, 3])
    want2 = hf_greedy(hf, ids2, 6, pixel_values=torch.tensor(two_pix),
                      image_grid_thw=torch.tensor(two_grid))

    text_ids = [5, 17, 93, 41, 7]
    cur = list(text_ids)
    with torch.no_grad():
        for _ in range(6):
            logits = hf(input_ids=torch.tensor([cur])).logits[0, -1]
            cur.append(int(logits.argmax()))
    wantt = cur[len(text_ids):]

    llm = make_llm(model_dir, max_prefill_tokens=8, min_prefill_tokens=4)
    outs = llm.generate(
        prompt_token_ids=[ids2, text_ids],
        mm_inputs=[{"pixel_values": two_pix, "image_grid_thw": two_grid},
                   None],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))
    assert outs[0].output_token_ids == want2, (outs[0].output_token_ids,
                                               want2)
    assert outs[1].output_token_ids == wantt


def test_vl3_video_per_frame_spans(vl3_ckpt):
    """t=2 video: HF splits the grid into per-frame spans (timestamp text
    between); our engine must normalize grids the same way."""
    model_dir, hf = vl3_ckpt
    rng = np.random.default_rng(7)
    pix, grid, _ = make_image(rng, (2, 4, 4))
    per_frame = 1 * 2 * 2
    # <t1> <vstart> frame1 <vend> <t2> <vstart> frame2 <vend> text
    ids = ([5, 11] + [VSTART] + [VID] * per_frame + [VEND]
           + [12] + [VSTART] + [VID] * per_frame + [VEND] + [7, 30])
    want = hf_greedy(hf, ids, 6,
                     pixel_values_videos=torch.tensor(pix),
                     video_grid_thw=torch.tensor(grid))

    llm = make_llm(model_dir)
    got = llm.generate(
        prompt_token_ids=[ids],
        mm_inputs=[{"video_pixel_values": pix, "video_grid_thw": grid}],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))[0]
    assert got.output_token_ids == want, (got.output_token_ids, want)


def test_vl3_prefix_cache_cold_warm(vl3_ckpt):
    model_dir, _ = vl3_ckpt
    rng = np.random.default_rng(9)
    pix, grid, n_tok = make_image(rng, (1, 4, 4))
    ids = vl_prompt([5, 9, 23, 8], n_tok, [7, 30, 2, 2, 9])
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    llm = make_llm(model_dir, prefix=True)

    def run():
        return llm.generate(
            prompt_token_ids=[ids],
            mm_inputs=[{"pixel_values": pix, "image_grid_thw": grid}],
            sampling_params=sp)[0].output_token_ids

    cold = run()
    hits0 = llm.memory_manager.hit_tokens
    warm = run()
    assert warm == cold
    assert llm.memory_manager.hit_tokens > hits0


MOE_TEXT = dict(
    vocab_size=160, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, head_dim=16,
    intermediate_size=96, moe_intermediate_size=32, num_experts=4,
    num_experts_per_tok=2, norm_topk_prob=True, decoder_sparse_step=1,
    max_position_embeddings=512, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False,
    rope_scaling={"rope_type": "default", "mrope_section": [2, 3, 3],
                  "mrope_interleaved": True},
)


@pytest.fixture(scope="module")
def vl3_moe_ckpt(tmp_path_factory):
    from transformers import (Qwen3VLMoeConfig,
                              Qwen3VLMoeForConditionalGeneration)
    torch.manual_seed(23)
    cfg = Qwen3VLMoeConfig(
        text_config=MOE_TEXT, vision_config=VISION,
        image_token_id=IMG, video_token_id=VID,
        vision_start_token_id=VSTART, vision_end_token_id=VEND,
        eos_token_id=0, bos_token_id=1)
    model = Qwen3VLMoeForConditionalGeneration(cfg)
    model.eval()
    d = tmp_path_factory.mktemp("tiny_vl3_moe")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


def test_vl3_moe_greedy_equivalence(vl3_moe_ckpt):
    model_dir, hf = vl3_moe_ckpt
    rng = np.random.default_rng(1)
    pix, grid, n_tok = make_image(rng)
    ids = vl_prompt([5, 9, 23], n_tok, [7, 30])
    want = hf_greedy(hf, ids, 6, pixel_values=torch.tensor(pix),
                     image_grid_thw=torch.tensor(grid))

    llm = make_llm(model_dir)
    got = llm.generate(
        prompt_token_ids=[ids],
        mm_inputs=[{"pixel_values": pix, "image_grid_thw": grid}],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))[0]
    assert got.output_token_ids == want, (got.output_token_ids, want)
