"""Weight quantization: int8 / fp8 / int4 weight-only, and W8A8.

TPU-native counterpart of the reference's quantization stack
(/root/reference/gllm/layers/quantization/fp8.py W8A8 block GEMM + int4
Marlin MoE, layers/moe/fused_moe_triton/layer.py:229-552): the reference
consumes prebuilt CUDA GEMMs; on TPU the idiomatic forms are

- **weight-only** (int8 / fp8 / packed int4): narrow storage + XLA-fused
  ``cast × scale`` in the matmul epilogue — halves/quarters HBM footprint
  and weight bandwidth (the decode bottleneck);
- **W8A8**: per-token activation quantization + an int8×int8 MXU matmul
  with f32 accumulation (TPU int8 matmul runs at double MACs/cycle),
  rescaled by the outer product of the activation and weight scales.

``Quantized``/``Quantized4``/``QuantizedW8A8`` are pytree nodes, so
quantized params flow through jit, donation, and NamedSharding exactly like
plain arrays; ``qmm`` dispatches on leaf type so model code is written once
(`qmm(x, lp["q_proj"])`). Routed-expert stacks ([L, E, in, out]) quantize
with the same per-output-channel machinery and are dequantized via ``deq``
in front of the ragged grouped GEMM.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp


class Quantized(NamedTuple):
    """Per-output-channel symmetric quantization: w ≈ q * scale."""
    q: jnp.ndarray        # [..., in, out] int8 (or float8)
    scale: jnp.ndarray    # [..., 1, out] f32


class Quantized4(NamedTuple):
    """Packed int4 (two nibbles per byte along the input axis)."""
    q: jnp.ndarray        # [..., in/2, out] int8, hi/lo nibbles
    scale: jnp.ndarray    # [..., 1, out] f32


class QuantizedW8A8(NamedTuple):
    """int8 weights whose matmul also quantizes activations per token."""
    q: jnp.ndarray        # [..., in, out] int8
    scale: jnp.ndarray    # [..., 1, out] f32


BLOCK = 128   # block-scale tile edge (reference fp8.py weight_block_size)


class QuantizedBlock(NamedTuple):
    """Block-wise fp8: one f32 scale per 128×128 weight tile (the
    reference's W8A8 block-fp8 checkpoint layout, fp8.py:370-453 — DeepSeek
    V3-class fp8 checkpoints ship exactly these scales)."""
    q: jnp.ndarray        # [..., in, out] float8
    scale: jnp.ndarray    # [..., ceil(in/128), ceil(out/128)] f32


def quantize_weight(w: jnp.ndarray, dtype=jnp.int8) -> Quantized:
    """Quantize a [..., in, out] matmul weight per output channel."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    if dtype == jnp.int8:
        scale = absmax / 127.0
        q = jnp.clip(jnp.round(wf / jnp.maximum(scale, 1e-9)),
                     -127, 127).astype(jnp.int8)
    else:  # float8 family
        fmax = float(jnp.finfo(dtype).max)
        scale = absmax / fmax
        q = (wf / jnp.maximum(scale, 1e-9)).astype(dtype)
    return Quantized(q, scale)


def quantize_weight_int4(w: jnp.ndarray) -> Quantized4:
    """Per-output-channel int4, packed two-per-byte on the input axis
    (the role of the reference's Marlin int4 path)."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = absmax / 7.0
    q = jnp.clip(jnp.round(wf / jnp.maximum(scale, 1e-9)),
                 -8, 7).astype(jnp.int8)
    *lead, K, N = q.shape
    if K % 2:
        raise ValueError(f"int4 packing needs an even input dim, got {K}")
    q = q.reshape(*lead, K // 2, 2, N)
    packed = ((q[..., 0, :] & 0x0F)
              | ((q[..., 1, :] & 0x0F) << 4)).astype(jnp.int8)
    return Quantized4(packed, scale)


def quantize_weight_block(w: jnp.ndarray,
                          dtype=jnp.float8_e4m3fn) -> QuantizedBlock:
    """Quantize a [..., in, out] weight with per-128×128-tile scales.
    Ragged tails pad with zeros for the absmax; the stored payload keeps
    the original shape."""
    wf = w.astype(jnp.float32)
    *lead, K, N = wf.shape
    kb, nb = -(-K // BLOCK), -(-N // BLOCK)
    wp = jnp.pad(wf, [(0, 0)] * len(lead)
                 + [(0, kb * BLOCK - K), (0, nb * BLOCK - N)])
    tiles = wp.reshape(*lead, kb, BLOCK, nb, BLOCK)
    absmax = jnp.max(jnp.abs(tiles), axis=(-3, -1))          # [..., kb, nb]
    fmax = float(jnp.finfo(dtype).max)
    scale = jnp.maximum(absmax / fmax, 1e-9)
    q = (tiles / scale[..., :, None, :, None]).reshape(
        *lead, kb * BLOCK, nb * BLOCK)[..., :K, :N].astype(dtype)
    return QuantizedBlock(q, scale)


def _unpack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """[..., in/2, out] packed → [..., in, out] int8 in [-8, 7]."""
    lo = (q << 4).astype(jnp.int8) >> 4          # sign-extend low nibble
    hi = q >> 4                                  # arithmetic shift: high
    *lead, K2, N = q.shape
    return jnp.stack([lo, hi], axis=-2).reshape(*lead, K2 * 2, N)


def deq(w, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Dequantize any weight leaf to a dense array (expert stacks feed
    this into lax.ragged_dot)."""
    if isinstance(w, Quantized4):
        return (_unpack_int4(w.q).astype(dtype)
                * w.scale.astype(dtype))
    if isinstance(w, QuantizedBlock):
        K, N = w.q.shape[-2:]
        s = jnp.repeat(jnp.repeat(w.scale, BLOCK, axis=-2), BLOCK,
                       axis=-1)[..., :K, :N]
        return w.q.astype(dtype) * s.astype(dtype)
    if isinstance(w, (Quantized, QuantizedW8A8)):
        return w.q.astype(dtype) * w.scale.astype(dtype)
    return w


def qragged_dot(xs: jnp.ndarray, w, group_sizes: jnp.ndarray,
                expert_ids: jnp.ndarray = None) -> jnp.ndarray:
    """Grouped (ragged) GEMM against a plain or quantized expert stack
    ([E, in, out]); rows of ``xs`` are expert-sorted.

    ``QuantizedW8A8`` stacks run the int8×int8 MXU grouped GEMM with int32
    accumulation and rescale in the epilogue — per-token activation scale
    × per-(expert, output-channel) weight scale gathered by
    ``expert_ids`` ([R] i32, the row's expert). This is the compute-win
    analogue of the reference's fused quantized MoE GEMMs
    (layers/moe/fused_moe_triton/layer.py:229-552, quantization/fp8.py) —
    no dense dequantized copy of the expert stack exists anywhere.

    Weight-only stacks (int8/fp8/int4/fp8_block) dequantize into the GEMM
    transient by design: their contract is bf16 activations × narrow
    storage (the reference W4A16 Marlin semantics); TPU has no mixed
    int×bf16 MXU mode, so the cast rides the GEMM epilogue fusion."""
    if isinstance(w, QuantizedW8A8):
        assert expert_ids is not None, "W8A8 ragged GEMM needs expert ids"
        xf = xs.astype(jnp.float32)
        x_absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        x_scale = jnp.maximum(x_absmax / 127.0, 1e-9)
        xq = jnp.clip(jnp.round(xf / x_scale), -127, 127).astype(jnp.int8)
        acc = jax.lax.ragged_dot(
            xq, w.q, group_sizes,
            preferred_element_type=jnp.int32).astype(jnp.float32)
        w_scale = jnp.squeeze(w.scale.astype(jnp.float32),
                              axis=-2)[expert_ids]       # [R, out]
        return (acc * x_scale * w_scale).astype(xs.dtype)
    return jax.lax.ragged_dot(xs, deq(w, xs.dtype), group_sizes)


def qmm(x: jnp.ndarray, w) -> jnp.ndarray:
    """Matmul against a plain or quantized weight."""
    if isinstance(w, QuantizedW8A8):
        # per-token activation quantization → int8×int8 MXU matmul with
        # f32 accumulation (reference fp8.py W8A8 block GEMM analogue)
        xf = x.astype(jnp.float32)
        x_absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        x_scale = jnp.maximum(x_absmax / 127.0, 1e-9)
        xq = jnp.clip(jnp.round(xf / x_scale), -127, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, w.q, (((xq.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32)
        return (acc * x_scale * w.scale.astype(jnp.float32)
                ).astype(x.dtype)
    if isinstance(w, (Quantized, Quantized4, QuantizedBlock)):
        return x @ deq(w, x.dtype)
    return x @ w


# Matmul leaves of the model layer groups that get quantized (norms,
# biases, rope tables, routers, and embeddings stay high-precision — same
# policy as the reference's ignored-layers audit, model_loader.py:122-174).
# Routed-expert stacks are included (the reference's weight-only path
# skipped them; its int4 Marlin path is the expert-quantizing one).
QUANT_LEAVES = frozenset({
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
    "q_b_proj", "shared_gate_proj", "shared_up_proj", "shared_down_proj",
    "w_gate", "w_up", "w_down",
    "in_qkvz", "out_proj",                       # hybrid GDN projections
})

_MODES = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}


def quantize_params(params: dict, dtype=jnp.int8, mode: str = None) -> dict:
    """Quantize the eligible matmul leaves of a model param tree.

    ``mode``: int8 | fp8 | int4 | w8a8 (overrides ``dtype`` when given).
    """
    def make(v):
        if mode == "int4":
            return quantize_weight_int4(v)
        if mode == "fp8_block":
            return quantize_weight_block(v)
        if mode == "w8a8":
            qz = quantize_weight(v, jnp.int8)
            return QuantizedW8A8(qz.q, qz.scale)
        if mode is not None and mode not in _MODES:
            raise ValueError(f"unknown quantization mode {mode!r}")
        return quantize_weight(v, _MODES[mode] if mode else dtype)

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            elif k in QUANT_LEAVES:
                out[k] = make(v)
            else:
                out[k] = v
        return out

    return walk(params)


def param_bytes(params) -> int:
    return sum(x.nbytes for x in jax.tree.leaves(params))
