"""Qwen2.5-VL: vision tower + dense GQA LM with mrope.

Reference: /root/reference/gllm/models/qwen2_5_vl.py (1045 LoC). The LM half
IS the Qwen2 dense decoder (reference derives it the same way) — we reuse
gllm_tpu/models/dense.py wholesale; mrope and visual-row splicing ride in
via StepBatch.mrope_positions / mm_embeds (see dense.forward). This module
adds the vision tower (gllm_tpu/models/vision.py), the combined param
pytree, and the checkpoint rules for both halves.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from gllm_tpu.models import dense, vision
from gllm_tpu.models.config import ModelConfig

init_kv_cache = dense.init_kv_cache
compute_logits = dense.compute_logits
forward = dense.forward


def vision_cfg(cfg: ModelConfig) -> vision.VisionConfig:
    assert cfg.vision_config is not None
    return vision.from_hf_vision_config(cfg.vision_config)


def make_rope_table(cfg: ModelConfig) -> jnp.ndarray:
    # mrope indices can exceed the token count (video temporal axis); the
    # reference sizes its cache at 4x max_position (rotary_embedding.py:640).
    rot_dim = int(cfg.head_dim * cfg.partial_rotary_factor)
    from gllm_tpu.ops import compute_rope_cos_sin
    return compute_rope_cos_sin(rot_dim, cfg.max_position * 4,
                                cfg.rope_theta, cfg.rope_scaling)


def init_params(cfg: ModelConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> dict:
    params = dense.init_params(cfg, seed=seed, dtype=dtype)
    params["visual"] = vision.init_vision_params(vision_cfg(cfg),
                                                 seed=seed, dtype=dtype)
    return params


def _vl_rules(cfg: ModelConfig):
    from gllm_tpu.models.loader import dense_rules
    base = dense_rules(cfg)
    vcfg = vision_cfg(cfg)

    vis_leaves = {
        "norm1.weight": ("norm1", None),
        "norm2.weight": ("norm2", None),
        "attn.qkv.weight": ("qkv_w", "t"),
        "attn.qkv.bias": ("qkv_b", None),
        "attn.proj.weight": ("proj_w", "t"),
        "attn.proj.bias": ("proj_b", None),
        "mlp.gate_proj.weight": ("gate_w", "t"),
        "mlp.gate_proj.bias": ("gate_b", None),
        "mlp.up_proj.weight": ("up_w", "t"),
        "mlp.up_proj.bias": ("up_b", None),
        "mlp.down_proj.weight": ("down_w", "t"),
        "mlp.down_proj.bias": ("down_b", None),
    }
    merger_leaves = {
        "ln_q.weight": ("ln_q", None),
        "mlp.0.weight": ("fc1_w", "t"),
        "mlp.0.bias": ("fc1_b", None),
        "mlp.2.weight": ("fc2_w", "t"),
        "mlp.2.bias": ("fc2_b", None),
    }

    def patch_embed_tf(t: np.ndarray) -> dict:
        # HF Conv3d weight [H, C, tps, ps, ps] → [C*tps*ps*ps, H] matmul
        return {"patch_embed": t.reshape(vcfg.hidden_size, -1).T}

    def rule(name: str):
        # transformers >= 4.52 nests the LM under model.language_model.*
        if name.startswith("model.language_model."):
            name = "model." + name[len("model.language_model."):]
        elif name.startswith("model.visual."):
            name = name[len("model."):]
        if name.startswith("visual."):
            rest = name[len("visual."):]
            if rest == "patch_embed.proj.weight":
                return (("visual", "__multi__"), None, patch_embed_tf)
            if rest.startswith("blocks."):
                idx_s, _, leaf = rest[len("blocks."):].partition(".")
                if leaf in vis_leaves:
                    target, tf = vis_leaves[leaf]
                    return (("visual", "blocks", target), int(idx_s), tf)
                return None
            if rest.startswith("merger."):
                leaf = rest[len("merger."):]
                if leaf in merger_leaves:
                    target, tf = merger_leaves[leaf]
                    return (("visual", "merger", target), None, tf)
                return None
            return None
        return base(name)

    return rule


def load_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16,
                progress_cb=None, skip_visual: bool = False) -> dict:
    from gllm_tpu.models.loader import _load_params
    template = jax.eval_shape(lambda: init_params(cfg, dtype=dtype))
    return _load_params(model_dir, template, _vl_rules(cfg),
                        progress_cb, skip_visual=skip_visual)


def embed_mm(params, cfg: ModelConfig, pixels, grid_thw) -> jnp.ndarray:
    return vision.embed_single(params["visual"], vision_cfg(cfg), pixels,
                               grid_thw)
