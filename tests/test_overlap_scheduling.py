"""Overlap (chained on-device decode) must be byte-identical to sync."""

import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(41)
    d = tmp_path_factory.mktemp("ov_llama")
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    return str(d)


def run(model_dir, overlap, prompts, sp):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=128,
        overlap_scheduling=overlap,
        scheduler=SchedulerConfig(max_prefill_tokens=64, max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg)
    outs = llm.generate(prompt_token_ids=prompts, sampling_params=sp)
    assert llm.memory_manager.num_free_pages == \
        llm.memory_manager.allocator.num_total  # no page leaks
    return [(o.output_token_ids, o.finish_reason) for o in outs]


def test_overlap_matches_sync_long_decode(ckpt):
    sp = SamplingParams(temperature=0.0, max_tokens=20, ignore_eos=True)
    prompts = [[3, 14, 15], [9, 2, 6, 5, 3], [58, 9]]
    assert run(ckpt, True, prompts, sp) == run(ckpt, False, prompts, sp)


def test_overlap_matches_sync_with_eos(ckpt):
    # natural EOS can land mid-chain → the chained step's work is discarded
    # and pages are released late but exactly once
    sp = SamplingParams(temperature=0.0, max_tokens=30)
    prompts = [[i, i + 1, i + 2] for i in range(1, 12, 2)]
    assert run(ckpt, True, prompts, sp) == run(ckpt, False, prompts, sp)


def test_overlap_matches_sync_max_tokens_boundary(ckpt):
    sp = SamplingParams(temperature=0.0, max_tokens=1, ignore_eos=True)
    prompts = [[5, 6], [7, 8, 9]]
    assert run(ckpt, True, prompts, sp) == run(ckpt, False, prompts, sp)


def test_overlap_page_boundary_growth(ckpt):
    # page_size 4: decode repeatedly crosses page boundaries inside chains
    sp = SamplingParams(temperature=0.0, max_tokens=13, ignore_eos=True)
    prompts = [[3] * 7]
    assert run(ckpt, True, prompts, sp) == run(ckpt, False, prompts, sp)


def test_overlap_sampled_reproducible(ckpt):
    sp = SamplingParams(temperature=0.8, top_k=30, max_tokens=12,
                        ignore_eos=True)
    a = run(ckpt, True, [[4, 8], [15, 16]], sp)
    b = run(ckpt, True, [[4, 8], [15, 16]], sp)
    assert a == b


def test_overlap_single_seq_eos_midchain_no_leak(ckpt):
    # single seq finishing by EOS while its chained step is in flight: the
    # engine must drain the chain and release every page (review repro)
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128,
        overlap_scheduling=True,
        cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg)
    # find the eos organically: run greedy, use the 3rd generated token as eos
    probe = llm.generate(prompt_token_ids=[[5, 6, 7]],
                         sampling_params=SamplingParams(
                             temperature=0.0, max_tokens=8, ignore_eos=True))
    eos = probe[0].output_token_ids[2]
    llm2 = LLM(config=cfg)
    llm2.eos_token_ids = frozenset([eos])
    out = llm2.generate(prompt_token_ids=[[5, 6, 7]],
                        sampling_params=SamplingParams(temperature=0.0,
                                                       max_tokens=30))[0]
    assert out.finish_reason == "stop"
    assert not llm2._in_flight
    assert llm2.memory_manager.num_free_pages == \
        llm2.memory_manager.allocator.num_total


def run_multi(model_dir, multi, prompts, sp, depth=2):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=128,
        overlap_scheduling=True, overlap_depth=depth,
        multi_step_decode=multi,
        scheduler=SchedulerConfig(max_prefill_tokens=64, max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg)
    outs = llm.generate(prompt_token_ids=prompts, sampling_params=sp)
    assert llm.memory_manager.num_free_pages == \
        llm.memory_manager.allocator.num_total
    return [(o.output_token_ids, o.finish_reason) for o in outs]


def test_multi_step_matches_sync_greedy(ckpt):
    """K fused decode steps per dispatch == plain sync, byte for byte
    (incl. page-boundary crossings inside the fused block)."""
    sp = SamplingParams(temperature=0.0, max_tokens=23, ignore_eos=True)
    prompts = [[3, 14, 15], [9, 2, 6, 5, 3], [58, 9]]
    want = run(ckpt, False, prompts, sp)
    assert run_multi(ckpt, 4, prompts, sp) == want
    assert run_multi(ckpt, 8, prompts, sp, depth=3) == want


def test_multi_step_matches_sync_with_eos(ckpt):
    """EOS lands mid-block → the rest of the fused block's tokens for that
    seq are discarded; frees happen exactly once."""
    sp = SamplingParams(temperature=0.0, max_tokens=30)
    prompts = [[i, i + 1, i + 2] for i in range(1, 12, 2)]
    assert run_multi(ckpt, 6, prompts, sp) == run(ckpt, False, prompts, sp)


def test_multi_step_sampling_key_schedule_identical(ckpt):
    """Unseeded temp>0 sampling: the fused block folds the SAME per-step
    keys as single-step chaining, so outputs stay byte-identical."""
    sp = SamplingParams(temperature=0.8, top_p=0.9, max_tokens=12,
                        ignore_eos=True)
    prompts = [[3, 14, 15], [9, 2, 6]]
    assert run_multi(ckpt, 4, prompts, sp) == run(ckpt, True, prompts, sp)


def test_seeded_sampling_fused_multi_step(ckpt):
    """Seeded requests ride the fused multi-step block since r4: their
    draws are a pure function of (seed, out_step), which the fused scan
    advances on device — outputs byte-identical to the plain engine."""
    prompts = [[5, 17, 93, 41], [9, 9, 3, 77, 21, 60]]
    sps = [SamplingParams(temperature=0.9, seed=7, max_tokens=24,
                          ignore_eos=True),
           SamplingParams(temperature=0.7, seed=11, max_tokens=24,
                          ignore_eos=True)]
    base = run(ckpt, False, [list(p) for p in prompts], sps)
    fused = run_multi(ckpt, 4, [list(p) for p in prompts], sps)
    assert base == fused
