"""Persistent-slot decode batching: fused chains survive churn.

With ``decode_slot_batching`` a sequence finish no longer breaks the
fused decode chain — the finished row stays in the batch as a masked
HOLE (same pow2 shape signature, ``active_until=0``), newly decode-ready
sequences JOIN vacant holes at chain boundaries (``host_rows`` token
splice), and ``chain_under_prefill`` lets the chain yield one sync pass
to waiting prefill instead of unfusing until the queue drains. Oracle
throughout: byte-identity with the plain synchronous engine on the same
saved checkpoint, under mid-stream finishes AND arrivals.
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.memory_manager import make_memory_manager
from gllm_tpu.obs.steptrace import TRACE
from gllm_tpu.sampling_params import SamplingParams
from gllm_tpu.scheduler import Scheduler
from gllm_tpu.sequence import HOLE_SEQ_ID, Sequence

EOS = 2


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(47)
    d = tmp_path_factory.mktemp("slot_llama")
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    return str(d)


def _cfg(model, overlap, slot, cup, msd=8, depth=2):
    return EngineConfig(
        model=model, dtype="float32", max_model_len=128, max_num_seqs=16,
        overlap_scheduling=overlap, overlap_depth=depth,
        multi_step_decode=msd,
        decode_slot_batching=slot, chain_under_prefill=cup,
        scheduler=SchedulerConfig(max_prefill_tokens=64,
                                  max_decode_seqs=16),
        cache=CacheConfig(page_size=4, num_pages=256))


# mid-stream churn: wave 1 has staggered finishes (3 lands first, then
# 9, 14, ... while 40 keeps running); wave 2 arrives once every wave-1
# seq has a few output tokens — so finishes AND arrivals both land while
# chains are in flight
_W1_LENS, _W1_MAX = (12, 33, 7, 21, 5, 17), (23, 40, 9, 31, 3, 14)
_W2_LENS, _W2_MAX = (9, 6, 11, 8), (12, 18, 7, 10)


def _seqs(llm, lens, maxs, rng):
    return [llm._allocate_seq(
        rng.integers(1, 120, size=int(n)).tolist(),
        SamplingParams(temperature=0.0, max_tokens=m, ignore_eos=True))
        for n, m in zip(lens, maxs)]


def _run_churn(model_dir, overlap, slot, cup):
    llm = LLM(config=_cfg(model_dir, overlap, slot, cup))
    rng = np.random.default_rng(7)
    wave1 = _seqs(llm, _W1_LENS, _W1_MAX, rng)
    wave2 = _seqs(llm, _W2_LENS, _W2_MAX, rng)
    for s in wave1:
        llm.add_seq(s)
    mark = TRACE.mark()
    added = False
    while llm.has_unfinished or not added:
        llm.step()
        if not added and min(s.num_output_tokens for s in wave1) >= 3:
            for s in wave2:
                llm.add_seq(s)
            added = True
    breaks = [e for e in TRACE.events(since=mark)
              if e["kind"] == "chain_break"]
    mm = llm.memory_manager
    assert mm.num_free_pages == mm.allocator.num_total
    assert not llm._in_flight
    toks = [s.output_token_ids for s in wave1 + wave2]
    assert [len(t) for t in toks] == list(_W1_MAX + _W2_MAX)
    return toks, breaks


def test_churn_byte_identity_and_break_accounting(ckpt):
    """Overlap under finishes+arrivals must match sync byte-for-byte in
    BOTH membership modes, and slot mode must break strictly less often
    than legacy: zero breaks blamed on a finish (holes absorb them) and
    at most one break per arrival (the grow/yield class) — legacy
    instead breaks on (at least) every mid-chain finish."""
    sync, _ = _run_churn(ckpt, False, False, 0)
    legacy, leg_breaks = _run_churn(ckpt, True, False, 0)
    slot, slot_breaks = _run_churn(ckpt, True, True, 8)
    assert legacy == sync          # flag off: byte-identical to current
    assert slot == sync            # slot mode: same tokens, fewer breaks
    assert len(slot_breaks) < len(leg_breaks)
    reasons = [b.get("reason") for b in slot_breaks]
    assert "finish" not in reasons, reasons
    # bounded by arrivals, not finishes: wave-2 admission may cost a
    # grow re-form and a ramp yield, dead rows must cost nothing
    assert len(slot_breaks) <= 2 * len(_W2_LENS), reasons
    assert any(b.get("reason") == "finish" for b in leg_breaks)
    # and every break is labeled with a taxonomy reason, both modes
    from gllm_tpu.obs.steptrace import CHAIN_BREAK_REASONS
    assert all(b.get("reason") in CHAIN_BREAK_REASONS
               for b in leg_breaks + slot_breaks)


def test_join_fills_hole_without_reform(ckpt):
    """A wave-2 arrival small enough to seat in an existing hole must
    JOIN the live chain (host_rows token splice) instead of forcing a
    sync re-form: the spy sees at least one chained dispatch whose
    host_rows is non-empty, and outputs still match sync."""
    # prompts small enough to prefill in ONE pass, so all four decode in
    # the same chain (staggered prefills would put the finisher in its
    # own batch and no hole would ever face the arrival); depth 3 keeps
    # the chain tip un-collected across the arrival's prefill yield
    llm = LLM(config=_cfg(ckpt, True, True, 8, depth=3))
    rng = np.random.default_rng(7)
    # one quick finisher (creates the hole) + three long runners (keep
    # the chain alive), then ONE late arrival to take the hole
    wave1 = _seqs(llm, (8, 9, 10, 5), (40, 40, 40, 3), rng)
    late = _seqs(llm, (6,), (10,), rng)
    joined = []
    orig = llm.runner._splice_chain_tokens

    def spy(batch, prev_tokens, host_rows):
        if host_rows:
            joined.append(list(host_rows))
        return orig(batch, prev_tokens, host_rows)

    llm.runner._splice_chain_tokens = spy
    for s in wave1:
        llm.add_seq(s)
    added = False
    while llm.has_unfinished or not added:
        llm.step()
        if not added and wave1[3].finish_reason is not None:
            llm.add_seq(late[0])   # the hole already exists when this
            added = True           # seq becomes decode-ready
    toks = [s.output_token_ids for s in wave1 + late]
    assert joined, "arrival never joined a vacant slot"

    sync = LLM(config=_cfg(ckpt, False, False, 0))
    rng = np.random.default_rng(7)
    w1 = _seqs(sync, (8, 9, 10, 5), (40, 40, 40, 3), rng)
    l2 = _seqs(sync, (6,), (10,), rng)
    outs = sync.generate(
        prompt_token_ids=[s.token_ids[:s.prompt_len] for s in w1 + l2],
        sampling_params=[s.sampling_params for s in w1 + l2])
    assert toks == [o.output_token_ids for o in outs]


# ---------------------------------------------------------------------------
# scheduler-level slot accounting (no model, pure host)
# ---------------------------------------------------------------------------


def _sched(slot=True, maxd=8, num_pages=128, max_num_seqs=32):
    cfg = EngineConfig(
        max_model_len=num_pages * 4,
        max_num_seqs=max_num_seqs,
        overlap_scheduling=True,
        decode_slot_batching=slot,
        scheduler=SchedulerConfig(max_prefill_tokens=256,
                                  max_decode_seqs=maxd),
        cache=CacheConfig(page_size=4, num_pages=num_pages))
    mm = make_memory_manager(num_pages, 4, False)
    return Scheduler(cfg, mm)


def _to_decode(sched, n, max_tokens=50, first_id=0):
    """Admit n seqs and run their prefill; returns them decode-ready."""
    seqs = [Sequence(first_id + i, [1, 3, 4, 5],
                     SamplingParams(max_tokens=max_tokens))
            for i in range(n)]
    for s in seqs:
        sched.add_seq(s)
    b = sched.schedule_once()
    assert b.num_seqs == n and b.items[0].samples
    sched.process_output(b, [7] * n, EOS)
    return seqs


def test_finish_becomes_hole_not_break():
    sched = _sched()
    _to_decode(sched, 3)
    b0 = sched.schedule_once()           # decode over all 3, in flight
    c1 = sched.schedule_chain(b0, 1)
    assert len(c1) == 1
    # seq 2 hits EOS while c1 is still in flight
    sched.process_output(b0, [7, 7, EOS], EOS)
    c2 = sched.schedule_chain(c1[0], 1)
    assert len(c2) == 1 and c2[0].num_seqs == 3     # signature survives
    assert c2[0].items[2].seq.seq_id == HOLE_SEQ_ID
    assert c2[0].active_until == [1, 1, 0]          # hole dead all block
    assert c2[0].host_rows is None
    sched.process_output(c1[0], [7, 7, 9], EOS)     # dead token dropped
    sched.process_output(c2[0], [7, 7, 9], EOS)
    # prefill + b0 + c1 + c2 samples for the two survivors; the dead
    # row's c1/c2 tokens were discarded
    assert [len(s.output_token_ids) for s in sched.running] == [4, 4]


def test_legacy_finish_breaks_chain():
    sched = _sched(slot=False)
    _to_decode(sched, 3)
    b0 = sched.schedule_once()
    c1 = sched.schedule_chain(b0, 1)
    sched.process_output(b0, [7, 7, EOS], EOS)
    assert sched.schedule_chain(c1[0], 1) == []
    assert sched.chain_break_reason == "finish"


def test_ready_seq_joins_hole_with_host_tokens():
    sched = _sched()
    _to_decode(sched, 3)
    b0 = sched.schedule_once()
    c1 = sched.schedule_chain(b0, 1)
    sched.process_output(b0, [7, 7, EOS], EOS)      # row 2 → hole
    c2 = sched.schedule_chain(c1[0], 1)
    sched.process_output(c1[0], [7, 7, 9], EOS)
    late = _to_decode(sched, 1, first_id=10)[0]     # decode-ready joiner
    c3 = sched.schedule_chain(c2[0], 1)
    assert len(c3) == 1 and c3[0].num_seqs == 3
    assert c3[0].host_rows == [2]                   # spliced from host
    assert c3[0].items[2].seq is late
    assert c3[0].active_until is None               # everyone alive again
    sched.process_output(c2[0], [7, 7, 9], EOS)
    sched.process_output(c3[0], [7, 7, 7], EOS)
    assert late.output_token_ids == [7, 7]


def test_unseatable_arrival_breaks_with_waiting():
    """More ready seqs than holes: the batch must grow past its shape
    signature — refuse with reason=waiting so the engine re-forms."""
    sched = _sched()
    _to_decode(sched, 3)
    b0 = sched.schedule_once()
    c1 = sched.schedule_chain(b0, 1)
    sched.process_output(b0, [7, 7, 7], EOS)        # nobody finished
    _to_decode(sched, 2, first_id=10)               # 2 ready, 0 holes
    assert sched.schedule_chain(c1[0], 1) == []
    assert sched.chain_break_reason == "waiting"


def test_drained_batch_compacts_below_bucket():
    """Occupancy under the next pow2 seq bucket boundary → the chain
    re-forms (compaction) instead of dragging dead rows forever."""
    sched = _sched(maxd=16, num_pages=256)
    _to_decode(sched, 16)
    b0 = sched.schedule_once()
    assert b0.num_seqs == 16
    c1 = sched.schedule_chain(b0, 1)
    # 9 of 16 finish while c1 is in flight → live 7 < bucket 8
    toks = [EOS] * 9 + [7] * 7
    sched.process_output(b0, toks, EOS)
    assert sched.schedule_chain(c1[0], 1) == []
    assert sched.chain_break_reason == "shape"
    sched.process_output(c1[0], [7] * 16, EOS)
    # at 10 live (bucket 16 with 16 slots... still >= boundary 8 after
    # only 6 finish) the chain would have survived: recheck the boundary
    sched2 = _sched(maxd=16, num_pages=256)
    _to_decode(sched2, 16, first_id=100)
    b0 = sched2.schedule_once()
    c1 = sched2.schedule_chain(b0, 1)
    sched2.process_output(b0, [EOS] * 6 + [7] * 10, EOS)
    c2 = sched2.schedule_chain(c1[0], 1)
    assert c2 and c2[0].num_seqs == 16
    assert sum(1 for it in c2[0].items
               if it.seq.seq_id == HOLE_SEQ_ID) == 6
