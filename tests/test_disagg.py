"""Encoder disaggregation: discovery, transfer, and the byte-identity
oracle.

The reference's correctness contract (docs/encoder_disaggregation_usage.md
§11, SURVEY.md §4.3): the disagg stack must be BYTE-IDENTICAL to the
monolith under greedy decoding, cold == warm. Plus failure-path coverage:
watchdog redispatch to a second encoder, give-up → abort.
"""

import os
import threading
import time

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.disagg.config import DisaggConfig
from gllm_tpu.disagg.discovery import (DiscoveryServer, NetworkDiscovery,
                                       make_payload)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams

IMG, VID, VSTART, VEND = 150, 151, 152, 153

TEXT = dict(
    vocab_size=160, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    max_position_embeddings=512, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False,
    rope_scaling={"type": "mrope", "mrope_section": [2, 2, 4]},
)
VISION = dict(
    depth=2, hidden_size=32, intermediate_size=48, num_heads=4,
    patch_size=2, temporal_patch_size=2, in_channels=3,
    spatial_merge_size=2, out_hidden_size=64, window_size=8,
    fullatt_block_indexes=[1], hidden_act="silu",
)

CHAT_TEMPLATE = (
    "{% for message in messages %}<im_start> "
    "{% if message['content'] is string %}{{ message['content'] }} "
    "{% else %}{% for content in message['content'] %}"
    "{% if content['type'] == 'image' %}"
    "<|vision_start|> <|image_pad|> <|vision_end|> "
    "{% elif content['type'] == 'text' %}{{ content['text'] }} "
    "{% endif %}{% endfor %}{% endif %}<im_end> {% endfor %}"
    "{% if add_generation_prompt %}<im_start> {% endif %}")


@pytest.fixture(scope="module")
def vl_ckpt(tmp_path_factory):
    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import (Qwen2_5_VLConfig,
                              Qwen2_5_VLForConditionalGeneration,
                              Qwen2TokenizerFast)
    from transformers.models.qwen2_vl.image_processing_qwen2_vl import (
        Qwen2VLImageProcessor)
    torch.manual_seed(31)
    cfg = Qwen2_5_VLConfig(
        text_config=TEXT, vision_config=VISION,
        image_token_id=IMG, video_token_id=VID,
        vision_start_token_id=VSTART, vision_end_token_id=VEND,
        eos_token_id=0, bos_token_id=1)
    model = Qwen2_5_VLForConditionalGeneration(cfg)
    model.eval()
    d = str(tmp_path_factory.mktemp("tiny_vl_disagg"))
    model.save_pretrained(d, safe_serialization=True)

    vocab = {f"w{i}": i for i in range(150)}
    vocab.update({"<|image_pad|>": IMG, "<|video_pad|>": VID,
                  "<|vision_start|>": VSTART, "<|vision_end|>": VEND,
                  "<unk>": 154, "<im_start>": 155, "<im_end>": 156})
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.WhitespaceSplit()
    t = Qwen2TokenizerFast(tokenizer_object=tok, unk_token="<unk>",
                           eos_token="w0", pad_token="w0",
                           chat_template=CHAT_TEMPLATE)
    t.save_pretrained(d)
    Qwen2VLImageProcessor(patch_size=2, temporal_patch_size=2, merge_size=2,
                          min_pixels=16, max_pixels=4096).save_pretrained(d)
    return d


def pil_image(seed=0, size=8):
    from PIL import Image
    arr = (np.random.default_rng(seed).random((size, size, 3))
           * 255).astype(np.uint8)
    return Image.fromarray(arr)


def make_llm(model_dir, prefix=False, **sched):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=256,
        scheduler=SchedulerConfig(**sched) if sched else SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=prefix))
    return LLM(config=cfg)


def drive(llm, seqs, timeout=60.0):
    """Run the engine loop until the given seqs finish."""
    deadline = time.monotonic() + timeout
    while any(not s.is_finished for s in seqs):
        assert time.monotonic() < deadline, "disagg drive timed out"
        llm.step()
    return [s.output_token_ids for s in seqs]


# ---------------------------------------------------------------------------
# Unit: discovery + transfer
# ---------------------------------------------------------------------------

def test_discovery_publish_expire_republish():
    srv = DiscoveryServer("127.0.0.1", 0, default_ttl_ms=200).start()
    try:
        a = NetworkDiscovery(f"127.0.0.1:{srv.port}", ttl_ms=200)
        b = NetworkDiscovery(f"127.0.0.1:{srv.port}", ttl_ms=200)
        payload = make_payload(role="encoder", addr="127.0.0.1:1")
        a.publish("enc0", payload)
        evs = b.poll_events("encoder")
        assert [(e.kind, e.identity) for e in evs] == [("ADD", "enc0")]
        assert b.poll_events("encoder") == []      # no change
        # lease renewal keeps it alive past the ttl
        time.sleep(0.4)
        assert b.poll_events("encoder") == []
        assert "enc0" in b.list("encoder")
        # close() revokes → REMOVE
        a.close()
        time.sleep(0.3)
        evs = b.poll_events("encoder")
        assert [(e.kind, e.identity) for e in evs] == [("REMOVE", "enc0")]
        b.close()
    finally:
        srv.stop()


def test_slot_pool_write_and_stale_guard():
    from gllm_tpu.disagg.transfer import SlotPool, TransferClient
    pool = SlotPool(num_slots=2, max_tokens=8, feat_dim=4,
                    host="127.0.0.1")
    try:
        cli = TransferClient(f"127.0.0.1:{pool.port}")
        slot = pool.alloc()
        pool.expect(7, 0, slot)
        emb = np.arange(12, dtype=np.float32).reshape(3, 4)
        cli.write(7, 0, slot, emb)
        landed = pool.drain_landed()
        assert landed == {(7, 0): (slot, 3)}
        np.testing.assert_array_equal(pool.clone(slot, 3), emb)
        # un-reserved write is dropped (stale)
        other = pool.alloc()
        cli.write(9, 0, other, emb)          # no expect() → stale
        assert pool.drain_landed() == {}
        cli.close()
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# E2E: disagg == monolith byte identity
# ---------------------------------------------------------------------------

@pytest.fixture()
def disagg_stack(vl_ckpt):
    """discovery + one encoder + one disagg LM engine, all in-process."""
    from gllm_tpu.disagg.encoder_runtime import EncoderEngine, EncoderRuntime
    srv = DiscoveryServer("127.0.0.1", 0).start()
    endpoint = f"127.0.0.1:{srv.port}"
    enc = EncoderRuntime(EncoderEngine(vl_ckpt, dtype="float32"),
                         endpoint, encoder_id="enc0").start()
    llm = make_llm(vl_ckpt)
    llm.init_disagg(DisaggConfig(
        is_lm=True, discovery_endpoint=endpoint, num_slots=4,
        max_vis_tokens=64, overlap=True))
    yield llm, srv, endpoint
    llm.disagg_coordinator.close()
    enc.stop()
    srv.stop()


MESSAGES = [{"role": "user", "content": [
    {"type": "image", "image": pil_image(3)},
    {"type": "text", "text": "w5 w9 w23"}]}]

TWO_IMG_MESSAGES = [{"role": "user", "content": [
    {"type": "image", "image": pil_image(3)},
    {"type": "text", "text": "w5 w9"},
    {"type": "image", "image": pil_image(4)},
    {"type": "text", "text": "w23 w7"}]}]


def monolith_tokens(vl_ckpt, messages, n=8):
    llm = make_llm(vl_ckpt)
    ids, mm_input = llm.process_mm_messages(messages)
    out = llm.generate(prompt_token_ids=[ids], mm_inputs=[mm_input],
                       sampling_params=SamplingParams(
                           temperature=0.0, max_tokens=n, ignore_eos=True))
    return out[0].output_token_ids


def submit_disagg(llm, messages, n=8):
    ids, items = llm.encode_skeleton(messages)
    seq = llm._allocate_seq(ids, SamplingParams(
        temperature=0.0, max_tokens=n, ignore_eos=True))
    llm.submit_disagg(seq, items)
    return seq


def test_disagg_byte_identity(disagg_stack, vl_ckpt):
    llm, _, _ = disagg_stack
    want = monolith_tokens(vl_ckpt, MESSAGES)
    seq = submit_disagg(llm, MESSAGES)
    got = drive(llm, [seq])[0]
    assert got == want, (got, want)
    # warm (encoder-side embed cache + fresh slots) — identical again
    seq2 = submit_disagg(llm, MESSAGES)
    assert drive(llm, [seq2])[0] == want


def test_disagg_two_images_chunked_prefill(vl_ckpt):
    """Two images through chunked prefill on the disagg LM (gate B
    exercises the per-span cap) — byte-identical to the monolith."""
    from gllm_tpu.disagg.encoder_runtime import EncoderEngine, EncoderRuntime
    want = monolith_tokens(vl_ckpt, TWO_IMG_MESSAGES, n=6)
    srv = DiscoveryServer("127.0.0.1", 0).start()
    endpoint = f"127.0.0.1:{srv.port}"
    enc = EncoderRuntime(EncoderEngine(vl_ckpt, dtype="float32"),
                         endpoint, encoder_id="enc0").start()
    llm = make_llm(vl_ckpt, max_prefill_tokens=8, min_prefill_tokens=4)
    llm.init_disagg(DisaggConfig(
        is_lm=True, discovery_endpoint=endpoint, num_slots=4,
        max_vis_tokens=64, overlap=True))
    try:
        seq = submit_disagg(llm, TWO_IMG_MESSAGES, n=6)
        got = drive(llm, [seq])[0]
        assert got == want, (got, want)
    finally:
        llm.disagg_coordinator.close()
        enc.stop()
        srv.stop()


def test_disagg_gate_b_blocks_until_ready(disagg_stack, vl_ckpt):
    """A slow encoder must not stall admission (gate A) — and prefill must
    wait at the unready span (gate B), then complete correctly."""
    llm, _, _ = disagg_stack
    want = monolith_tokens(vl_ckpt, MESSAGES)
    # slow the encoder's ViT path
    coord = llm.disagg_coordinator
    orig_clone = coord.pool.clone
    delay = {"n": 0}

    def slow_clone(slot_id, n):
        delay["n"] += 1
        return orig_clone(slot_id, n)

    coord.pool.clone = slow_clone
    seq = submit_disagg(llm, MESSAGES)
    got = drive(llm, [seq])[0]
    assert got == want
    assert delay["n"] >= 1        # embeddings actually came from the pool


def test_disagg_watchdog_redispatch(vl_ckpt, monkeypatch):
    """Encoder A drops the first 2 jobs (fail injection); the watchdog
    re-dispatches to encoder B and the request still completes
    byte-identically. Two images → round-robin hits both encoders, so at
    least one job lands on the dropper."""
    from gllm_tpu.disagg.encoder_runtime import EncoderEngine, EncoderRuntime
    want = monolith_tokens(vl_ckpt, TWO_IMG_MESSAGES, n=6)
    monkeypatch.setenv("GLLM_TPU_DISAGG_REDISPATCH_TIMEOUT_S", "0.5")
    monkeypatch.setenv("GLLM_TPU_DISAGG_MAX_REDISPATCH", "2")
    srv = DiscoveryServer("127.0.0.1", 0).start()
    endpoint = f"127.0.0.1:{srv.port}"
    monkeypatch.setenv("GLLM_TPU_ENC_FAIL_FIRST_N", "2")
    enc_a = EncoderRuntime(EncoderEngine(vl_ckpt, dtype="float32"),
                           endpoint, encoder_id="encA").start()
    monkeypatch.setenv("GLLM_TPU_ENC_FAIL_FIRST_N", "0")
    enc_b = EncoderRuntime(EncoderEngine(vl_ckpt, dtype="float32"),
                           endpoint, encoder_id="encB").start()
    llm = make_llm(vl_ckpt)
    llm.init_disagg(DisaggConfig(
        is_lm=True, discovery_endpoint=endpoint, num_slots=4,
        max_vis_tokens=64, overlap=True))
    try:
        seq = submit_disagg(llm, TWO_IMG_MESSAGES, n=6)
        got = drive(llm, [seq], timeout=90.0)[0]
        assert got == want, (got, want)
    finally:
        llm.disagg_coordinator.close()
        enc_a.stop()
        enc_b.stop()
        srv.stop()


def test_disagg_giveup_aborts(vl_ckpt, monkeypatch):
    """A single always-failing encoder: the watchdog gives up after max
    attempts and the seq is aborted (never hangs)."""
    from gllm_tpu.disagg.encoder_runtime import EncoderEngine, EncoderRuntime
    monkeypatch.setenv("GLLM_TPU_DISAGG_REDISPATCH_TIMEOUT_S", "0.3")
    monkeypatch.setenv("GLLM_TPU_DISAGG_MAX_REDISPATCH", "1")
    monkeypatch.setenv("GLLM_TPU_ENC_FAIL_FIRST_N", "100")
    srv = DiscoveryServer("127.0.0.1", 0).start()
    endpoint = f"127.0.0.1:{srv.port}"
    enc = EncoderRuntime(EncoderEngine(vl_ckpt, dtype="float32"),
                         endpoint, encoder_id="encA").start()
    llm = make_llm(vl_ckpt)
    llm.init_disagg(DisaggConfig(
        is_lm=True, discovery_endpoint=endpoint, num_slots=4,
        max_vis_tokens=64, overlap=True))
    try:
        seq = submit_disagg(llm, MESSAGES)
        deadline = time.monotonic() + 30
        while not seq.is_finished and time.monotonic() < deadline:
            llm.step()
        assert seq.is_finished
        assert seq.finish_reason == "abort"
        assert llm.disagg_coordinator.num_pending == 0
        # all slots back in the pool
        assert llm.disagg_coordinator.pool.num_free == 4
    finally:
        llm.disagg_coordinator.close()
        enc.stop()
        srv.stop()


def test_disagg_api_server_end_to_end(vl_ckpt):
    """OpenAI image request over HTTP against a disagg LM node."""
    import base64
    import http.client
    import io
    import json

    from gllm_tpu.disagg.encoder_runtime import EncoderEngine, EncoderRuntime
    from gllm_tpu.entrypoints.api_server import serve

    srv = DiscoveryServer("127.0.0.1", 0).start()
    endpoint = f"127.0.0.1:{srv.port}"
    enc = EncoderRuntime(EncoderEngine(vl_ckpt, dtype="float32"),
                         endpoint, encoder_id="enc0").start()
    llm = make_llm(vl_ckpt)
    llm.init_disagg(DisaggConfig(
        is_lm=True, discovery_endpoint=endpoint, num_slots=4,
        max_vis_tokens=64, overlap=True))
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        buf = io.BytesIO()
        pil_image(7).save(buf, format="PNG")
        url = ("data:image/png;base64,"
               + base64.b64encode(buf.getvalue()).decode())
        body = json.dumps({
            "messages": [{"role": "user", "content": [
                {"type": "image_url", "image_url": {"url": url}},
                {"type": "text", "text": "w5 w9"}]}],
            "max_tokens": 4, "temperature": 0, "ignore_eos": True})
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request("POST", "/v1/chat/completions", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = json.loads(resp.read())
        conn.close()
        assert resp.status == 200, data
        assert data["usage"]["completion_tokens"] == 4
    finally:
        httpd.shutdown()
        httpd.state.engine.shutdown()
        llm.disagg_coordinator.close()
        enc.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# E2E: disagg under dp / pp LM topologies (VERDICT r02 #9 — reference
# dispatches to encoder fleets from any LM topology, disagg/lm_manager.py)
# ---------------------------------------------------------------------------

def _parallel_llm(model_dir, **par):
    from gllm_tpu.config import ParallelConfig
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=256,
        cache=CacheConfig(page_size=4, num_pages=128),
        parallel=ParallelConfig(**par))
    return LLM(config=cfg)


@pytest.mark.parametrize("par", [dict(dp=2), dict(pp=2)],
                         ids=["dp2", "pp2"])
def test_disagg_parallel_lm_byte_identity(vl_ckpt, par):
    """A dp=2 / pp=2 LM node behind the same encoder fleet must be
    byte-identical to the single-replica monolith. Two requests under dp
    round-robin onto BOTH replicas."""
    from gllm_tpu.disagg.encoder_runtime import EncoderEngine, EncoderRuntime
    want = monolith_tokens(vl_ckpt, MESSAGES)
    want2 = monolith_tokens(vl_ckpt, TWO_IMG_MESSAGES)
    srv = DiscoveryServer("127.0.0.1", 0).start()
    endpoint = f"127.0.0.1:{srv.port}"
    enc = EncoderRuntime(EncoderEngine(vl_ckpt, dtype="float32"),
                         endpoint, encoder_id="enc0").start()
    llm = _parallel_llm(vl_ckpt, **par)
    llm.init_disagg(DisaggConfig(
        is_lm=True, discovery_endpoint=endpoint, num_slots=8,
        max_vis_tokens=64, overlap=True))
    try:
        seq = submit_disagg(llm, MESSAGES)
        seq2 = submit_disagg(llm, TWO_IMG_MESSAGES)
        got, got2 = drive(llm, [seq, seq2], timeout=120.0)
        assert got == want, (got, want)
        assert got2 == want2, (got2, want2)
    finally:
        llm.disagg_coordinator.close()
        enc.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# E2E: per-frame video over disagg (Qwen3-VL)
# ---------------------------------------------------------------------------

VL3_TEXT = dict(
    vocab_size=160, hidden_size=64, num_hidden_layers=3,
    num_attention_heads=4, num_key_value_heads=2, head_dim=16,
    intermediate_size=96, max_position_embeddings=512, rms_norm_eps=1e-6,
    rope_theta=10000.0, tie_word_embeddings=False,
    rope_scaling={"rope_type": "default", "mrope_section": [2, 3, 3],
                  "mrope_interleaved": True},
)
VL3_VISION = dict(
    depth=3, hidden_size=32, intermediate_size=48, num_heads=4,
    patch_size=2, temporal_patch_size=2, in_channels=3,
    spatial_merge_size=2, out_hidden_size=64, num_position_embeddings=16,
    deepstack_visual_indexes=[0, 2], hidden_act="gelu_pytorch_tanh",
)


@pytest.fixture(scope="module")
def vl3_ckpt(tmp_path_factory):
    from transformers import Qwen3VLConfig, Qwen3VLForConditionalGeneration
    torch.manual_seed(21)
    cfg = Qwen3VLConfig(
        text_config=VL3_TEXT, vision_config=VL3_VISION,
        image_token_id=IMG, video_token_id=VID,
        vision_start_token_id=VSTART, vision_end_token_id=VEND,
        eos_token_id=0, bos_token_id=1)
    model = Qwen3VLForConditionalGeneration(cfg)
    model.eval()
    d = str(tmp_path_factory.mktemp("tiny_vl3_disagg"))
    model.save_pretrained(d, safe_serialization=True)
    return d


def test_disagg_video_per_frame(vl3_ckpt):
    """t=2 video on a per-frame-video model (Qwen3-VL deepstack): the
    disagg admit path must apply the monolith's per-frame grid
    normalization (engine/mm.py build_mm_state) to the meta's raw (t,h,w)
    grid — byte-identity vs the monolith on the same expanded prompt.
    Covers the deepstack-wide embedding rows through the slot transfer."""
    rng = np.random.default_rng(9)
    t, h, w = 2, 4, 4
    pix = rng.standard_normal((t * h * w, 3 * 2 * 2 * 2)).astype(np.float32)
    grid = np.asarray([[t, h, w]])
    n_tok = t * (h // 2) * (w // 2)

    def make_vl3_llm():
        return LLM(config=EngineConfig(
            model=vl3_ckpt, dtype="float32", max_model_len=256,
            tokenizer="",
            cache=CacheConfig(page_size=4, num_pages=128)))

    full_ids = [5, VSTART] + [VID] * n_tok + [VEND, 7, 30]
    mono = make_vl3_llm()
    want = mono.generate(
        prompt_token_ids=[full_ids],
        mm_inputs=[{"video_pixel_values": pix, "video_grid_thw": grid}],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))[0].output_token_ids
    del mono

    from gllm_tpu.disagg.encoder_runtime import EncoderEngine, EncoderRuntime
    srv = DiscoveryServer("127.0.0.1", 0).start()
    endpoint = f"127.0.0.1:{srv.port}"
    enc = EncoderRuntime(EncoderEngine(vl3_ckpt, dtype="float32"),
                         endpoint, encoder_id="enc0").start()
    llm = make_vl3_llm()
    llm.init_disagg(DisaggConfig(
        is_lm=True, discovery_endpoint=endpoint, num_slots=4,
        max_vis_tokens=64, overlap=True))
    try:
        skeleton = [5, VSTART, VID, VEND, 7, 30]
        seq = llm._allocate_seq(skeleton, SamplingParams(
            temperature=0.0, max_tokens=6, ignore_eos=True))
        llm.submit_disagg(
            seq, [("video", {"pixel_values": pix,
                             "grid_thw": [t, h, w]})])
        got = drive(llm, [seq], timeout=90.0)[0]
        assert got == want, (got, want)
    finally:
        llm.disagg_coordinator.close()
        enc.stop()
        srv.stop()


def test_processor_hash_includes_pixel_bounds(tmp_path):
    """Runtime pixel-bound overrides change the effective preprocessing,
    so they must change the encoder/LM agreement hash — an encoder capped
    with --mm-processor-max-pixels and an uncapped LM must not pass the
    disagg preprocessing-agreement check."""
    from gllm_tpu.engine.mm_processing import processor_config_hash
    d = str(tmp_path)
    base = processor_config_hash(d)
    assert processor_config_hash(d) == base
    capped = processor_config_hash(d, max_pixels=50176)
    assert capped != base
    assert processor_config_hash(d, max_pixels=50176) == capped
    assert processor_config_hash(d, min_pixels=28 * 28,
                                 max_pixels=50176) != capped
