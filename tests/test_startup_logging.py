"""Startup-latency instrumentation (VERDICT r03 next #9).

The engine logs one structured ``[startup] phase=... seconds=...`` line per
startup phase (weight load, each warmup bucket compile, warmup total) — the
serving-readiness breakdown the reference gets from its CUDA-graph capture
logs (model_runner.py:1525-1615). These tests pin the lines' presence so
the instrumentation can't silently rot.
"""

import logging

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models.config import ModelConfig


def _tiny_llm():
    mcfg = ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=256, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=96, max_position=256)
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=64,
        max_num_seqs=8,
        scheduler=SchedulerConfig(max_prefill_tokens=32, max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=64))
    return LLM(config=cfg, model_cfg=mcfg)


def test_startup_phase_lines(caplog):
    with caplog.at_level(logging.INFO):
        llm = _tiny_llm()
        llm.runner.warmup()
    msgs = [r.getMessage() for r in caplog.records]
    assert any("[startup] phase=weight_load seconds=" in m for m in msgs)
    # per-bucket compile lines (decode and mixed prefill+decode variants)
    assert any("[startup] phase=warmup_bucket seqs=" in m
               and "pages=" in m for m in msgs)
    assert any("[startup] phase=warmup_bucket seqs=" in m
               and "prefill_chunk=" in m for m in msgs)
    # warmup total with bucket count
    assert any("[startup] phase=warmup seconds=" in m and "buckets=" in m
               for m in msgs)


def test_api_server_first_token_line(tmp_path):
    """The api_server CLI logs the serving-readiness yardstick
    (`[startup] phase=first_token`) after warmup — asserted through the
    real entrypoint in a subprocess."""
    import os
    import subprocess
    import sys
    import time

    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(3)
    d = tmp_path / "srv"
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from gllm_tpu.entrypoints.api_server import main\n"
        f"main(['--model', {str(d)!r}, '--tokenizer', '', '--port', '0',\n"
        "      '--max-model-len', '64', '--max-num-seqs', '8',\n"
        "      '--num-pages', '64', '--page-size', '4',\n"
        "      '--maxp', '32', '--maxd', '8'])\n")
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    log = tmp_path / "srv.log"
    with open(log, "w") as lf:
        proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                                stdout=lf, stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 300
        seen = False
        while time.monotonic() < deadline and not seen:
            time.sleep(2)
            seen = "phase=first_token" in log.read_text()
            assert proc.poll() is None or seen, log.read_text()[-2000:]
        assert seen, log.read_text()[-2000:]
        txt = log.read_text()
        assert "total_startup_seconds=" in txt
    finally:
        proc.kill()
        proc.wait(timeout=30)
