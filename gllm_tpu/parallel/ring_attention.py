"""Ring attention: sequence/context parallelism over an ``sp`` mesh axis.

The reference has NO sequence parallelism (SURVEY.md §2.2 row SP/CP —
long context is handled by chunked prefill + paged KV + MLA chunked-context).
This module goes beyond parity: causal ring attention for long-context
prefill, the TPU-native CP design — the sequence axis is sharded over the
``sp`` mesh axis, K/V shards rotate around the ring with
``jax.lax.ppermute`` (ICI neighbor exchanges), and each hop's partial
attention is merged with the running flash-attention state (LSE merge — the
same math as the reference's chunked-context merge_attn_states,
/root/reference/gllm/layers/ops/merge_attn_states.py).

Causality across shards: query shard q holds global positions
``[q*C, (q+1)*C)``; the K/V shard visiting from source ``s`` is
- fully visible when s < q (all its keys precede all queries),
- causally masked when s == q,
- fully masked (skipped) when s > q.

Usage: ``ring_attention(q, k, v, axis_name="sp")`` inside
``shard_map``/``pjit`` with q/k/v sharded on their sequence axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = float("-inf")


def _block_attention(q, k, v, scale, mask):
    """Plain f32 attention for one (q-shard, kv-shard) pair.

    Returns (out [T, Hq, D] unnormalized, m [T, Hq] rowmax,
    l [T, Hq] rowsum) for LSE merging.
    """
    Hq = q.shape[1]
    Hkv = k.shape[1]
    group = Hq // Hkv
    T, Ck = q.shape[0], k.shape[0]
    qh = q.reshape(T, Hkv, group, -1).astype(jnp.float32)
    scores = jnp.einsum("thgd,shd->thgs", qh, k.astype(jnp.float32)) * scale
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                         # [T, Hkv, g]
    # all-masked rows: keep m finite so exp() is well-defined
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(mask[:, None, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1)                              # [T, Hkv, g]
    out = jnp.einsum("thgs,shd->thgd", p, v.astype(jnp.float32))
    return (out.reshape(T, Hq, -1), m_safe.reshape(T, Hq),
            l.reshape(T, Hq))


def _merge(acc, m, l, out_b, m_b, l_b):
    """Merge a new partial-attention block into the running flash state."""
    m_new = jnp.maximum(m, m_b)
    a1 = jnp.exp(m - m_new)
    a2 = jnp.exp(m_b - m_new)
    acc = acc * a1[..., None] + out_b * a2[..., None]
    l_new = l * a1 + l_b * a2
    return acc, m_new, l_new


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   kv_valid=None, *, axis_name: str,
                   scale: Optional[float] = None,
                   axis_size: Optional[int] = None):
    """Causal ring attention inside shard_map.

    q: [C, Hq, D] local query shard (global seq sharded over axis_name)
    k/v: [C, Hkv, D] local key/value shards.
    kv_valid: optional replicated scalar — global token count actually
    valid; keys at positions >= kv_valid are masked everywhere (the
    engine's bucketed prefill pads the token axis, and a padded KEY at a
    fake position must not leak into real queries' softmax).
    Returns the local output shard [C, Hq, D].
    """
    C, Hq, D = q.shape
    if scale is None:
        scale = D ** -0.5
    if axis_size is not None:                  # static size from the mesh
        n = axis_size
    else:                                      # jax >= 0.6 only
        n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    pos_q = my * C + jnp.arange(C)

    acc = jnp.zeros((C, Hq, v.shape[-1]), jnp.float32)
    # finite -inf sentinel: keeps exp(m - m_new) well-defined before the
    # first contributing block
    m = jnp.full((C, Hq), -1e30, jnp.float32)
    l = jnp.zeros((C, Hq), jnp.float32)
    # mark the device-constant init values as varying over the ring axis so
    # the fori_loop carry type matches the per-shard results (pcast is the
    # vma-era API — 0.4.x shard_map has no vma tracking, nothing to mark)
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        acc, m, l = (pcast(x, (axis_name,), to="varying")
                     for x in (acc, m, l))

    def hop(i, carry):
        acc, m, l, k_cur, v_cur = carry
        src = jax.lax.rem(my - i + n, n)     # whose shard we hold this hop
        pos_k = src * C + jnp.arange(C)
        mask = pos_k[None, :] <= pos_q[:, None]
        if kv_valid is not None:
            mask = mask & (pos_k[None, :] < kv_valid)
        out_b, m_b, l_b = _block_attention(q, k_cur, v_cur, scale, mask)
        # skip fully-masked hops (src > my): l_b is all zero there and the
        # merge is a no-op because m_b is 0-masked rows with l_b=0.
        acc, m, l = _merge(acc, m, l, out_b, m_b, l_b)
        # rotate kv to the next device on the ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    acc, m, l, _, _ = jax.lax.fori_loop(0, n, hop, (acc, m, l, k, v))
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Optional[Mesh] = None,
                           axis_name: str = "sp",
                           scale: Optional[float] = None, kv_valid=None):
    """Shard q/k/v over ``axis_name`` on their sequence axis and run ring
    attention via shard_map.

    mesh=None binds the CONTEXT abstract mesh with only ``axis_name``
    manual — the form the serving step uses inside its jit trace (the
    other mesh axes stay GSPMD-auto); a concrete mesh is bound fully
    (standalone / unit-test use). ``kv_valid``: optional replicated scalar
    masking padded keys (see ring_attention)."""
    from gllm_tpu.parallel.mesh import (active_mesh,
                                        compat_shard_map as shard_map)

    spec = P(axis_name, None, None)
    kw = (dict(mesh=None, axis_names={axis_name}) if mesh is None
          else dict(mesh=mesh))
    m = mesh if mesh is not None else active_mesh()
    sizes = dict(getattr(m, "shape_tuple", None) or m.shape)
    part = functools.partial(ring_attention, axis_name=axis_name,
                             scale=scale, axis_size=sizes[axis_name])
    if kv_valid is None:
        fn = shard_map(part, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False, **kw)
        return fn(q, k, v)
    fn = shard_map(part, in_specs=(spec, spec, spec, P()),
                   out_specs=spec, check_vma=False, **kw)
    return fn(q, k, v, kv_valid)
