"""ChatGLM3 legacy layout + torch-.bin loader fallback.

No ChatGLM class ships in this image (the real checkpoint uses remote
code), but ChatGLM3's math IS the GLM base math (interleaved partial-half
rotary, SwiGLU, GQA — reference models/chatglm.py builds it from the same
layers as GLM4 minus sandwich norms). Oracle: take a transformers
``GlmForCausalLM``, re-serialize its weights under the ChatGLM3 checkpoint
layout (fused query_key_value / dense_h_to_4h, transformer.* namespacing,
legacy config keys) — the engine must produce HF-greedy-identical output
through the chatglm rules. The checkpoint is written as
``pytorch_model.bin`` to exercise the .bin fallback too.
"""

import json
import os

import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams

H, NH, NKV, HD, I, L, V = 64, 4, 2, 16, 96, 2, 128


@pytest.fixture(scope="module")
def chatglm_ckpt(tmp_path_factory):
    from transformers import GlmConfig, GlmForCausalLM
    torch.manual_seed(51)
    glm = GlmForCausalLM(GlmConfig(
        vocab_size=V, hidden_size=H, intermediate_size=I,
        num_hidden_layers=L, num_attention_heads=NH,
        num_key_value_heads=NKV, head_dim=HD,
        partial_rotary_factor=0.5, attention_bias=True,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False, eos_token_id=0,
        pad_token_id=0))
    glm.eval()

    sd = glm.state_dict()
    out = {}
    out["transformer.embedding.word_embeddings.weight"] = \
        sd["model.embed_tokens.weight"]
    out["transformer.encoder.final_layernorm.weight"] = \
        sd["model.norm.weight"]
    out["transformer.output_layer.weight"] = sd["lm_head.weight"]
    for i in range(L):
        src = f"model.layers.{i}."
        dst = f"transformer.encoder.layers.{i}."
        out[dst + "input_layernorm.weight"] = \
            sd[src + "input_layernorm.weight"]
        out[dst + "post_attention_layernorm.weight"] = \
            sd[src + "post_attention_layernorm.weight"]
        out[dst + "self_attention.query_key_value.weight"] = torch.cat(
            [sd[src + "self_attn.q_proj.weight"],
             sd[src + "self_attn.k_proj.weight"],
             sd[src + "self_attn.v_proj.weight"]], dim=0)
        out[dst + "self_attention.query_key_value.bias"] = torch.cat(
            [sd[src + "self_attn.q_proj.bias"],
             sd[src + "self_attn.k_proj.bias"],
             sd[src + "self_attn.v_proj.bias"]], dim=0)
        out[dst + "self_attention.dense.weight"] = \
            sd[src + "self_attn.o_proj.weight"]
        # HF Glm fuses gate_up exactly like ChatGLM's dense_h_to_4h
        # (first half gate, second half up)
        out[dst + "mlp.dense_h_to_4h.weight"] = \
            sd[src + "mlp.gate_up_proj.weight"]
        out[dst + "mlp.dense_4h_to_h.weight"] = \
            sd[src + "mlp.down_proj.weight"]

    d = str(tmp_path_factory.mktemp("tiny_chatglm3"))
    torch.save(out, os.path.join(d, "pytorch_model.bin"))
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({
            "architectures": ["ChatGLMModel"],
            "padded_vocab_size": V, "hidden_size": H, "num_layers": L,
            "num_attention_heads": NH, "multi_query_attention": True,
            "multi_query_group_num": NKV, "kv_channels": HD,
            "ffn_hidden_size": I, "layernorm_epsilon": 1e-5,
            "seq_length": 256, "add_qkv_bias": True,
            "add_bias_linear": False, "rope_ratio": 1.0,
            "rmsnorm": True, "eos_token_id": 0,
        }, f)
    return d, glm


def hf_greedy(model, prompt_ids, n):
    ids = list(prompt_ids)
    with torch.no_grad():
        for _ in range(n):
            logits = model(torch.tensor([ids])).logits[0, -1]
            ids.append(int(logits.argmax()))
    return ids[len(prompt_ids):]


def test_chatglm3_greedy_equivalence_from_bin(chatglm_ckpt):
    d, glm = chatglm_ckpt
    llm = LLM(config=EngineConfig(
        model=d, tokenizer="", dtype="float32", max_model_len=128,
        cache=CacheConfig(page_size=4, num_pages=64)))
    prompts = [[7, 3, 56, 21], [99, 14, 2, 61, 5]]
    got = [o.output_token_ids for o in llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))]
    for p, g in zip(prompts, got):
        assert g == hf_greedy(glm, p, 8), (p, g)


def test_bin_fallback_lazy_shards(chatglm_ckpt):
    from gllm_tpu.models.loader import LazySafetensors
    d, _ = chatglm_ckpt
    lazy = LazySafetensors(d)
    names = list(lazy.names())
    assert "transformer.output_layer.weight" in names
    t = lazy.get("transformer.embedding.word_embeddings.weight")
    assert t.shape == (V, H)
