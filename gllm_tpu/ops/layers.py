"""Elementwise / normalization ops.

The reference calls prebuilt CUDA kernels for these
(sgl_kernel rmsnorm / fused_add_rmsnorm / silu_and_mul — SURVEY.md §2.6). On
TPU they are plain jnp: XLA fuses them into the surrounding matmuls, which is
exactly what the hand-written CUDA fusions buy on GPU.

All norms accumulate in float32 and cast back to the input dtype, matching
HF/reference numerics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray,
             eps: float = 1e-6) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def fused_add_rms_norm(x: jnp.ndarray, residual: jnp.ndarray,
                       weight: jnp.ndarray, eps: float = 1e-6):
    """residual' = x + residual; y = rms_norm(residual').

    Mirrors the reference's fused_add_rmsnorm contract
    (/root/reference/gllm/layers/layernorm.py): returns (normed, new_residual).
    """
    new_residual = x + residual
    return rms_norm(new_residual, weight, eps), new_residual


def silu_and_mul(x: jnp.ndarray) -> jnp.ndarray:
    """x = [gate, up] concatenated on last dim → silu(gate) * up
    (reference layers/activation.py → sgl_kernel silu_and_mul)."""
    gate, up = jnp.split(x, 2, axis=-1)
    gf = gate.astype(jnp.float32)
    return ((gf * jax.nn.sigmoid(gf)).astype(x.dtype)) * up


def gelu_and_mul(x: jnp.ndarray) -> jnp.ndarray:
    gate, up = jnp.split(x, 2, axis=-1)
    return jax.nn.gelu(gate.astype(jnp.float32),
                       approximate=True).astype(x.dtype) * up
