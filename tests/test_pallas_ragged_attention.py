"""Ragged paged attention kernel vs the XLA oracle (interpret mode on CPU).

Covers mixed prefill+decode batches — the layout the engine emits for
chunked prefill (reference flash_attn_varlen_func semantics): each seq
attends to its cached context plus the causal part of its own new chunk.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from gllm_tpu.ops.attention import AttentionMetadata, _xla_paged_attention
from gllm_tpu.ops.pallas.ragged_attention import ragged_paged_attention


def build_case(rng, seqs, Hq, Hkv, D, page, num_pages, pad_seqs=0):
    """seqs: list of (q_len, kv_len) with kv_len >= q_len (context includes
    the new tokens, matching the engine's post-step kv_lens)."""
    S = len(seqs) + pad_seqs
    T = sum(q for q, _ in seqs)
    k_cache = rng.standard_normal((num_pages, page, Hkv, D)).astype(
        np.float32)
    v_cache = rng.standard_normal((num_pages, page, Hkv, D)).astype(
        np.float32)
    max_pages = max(-(-kv // page) for _, kv in seqs)
    pt = np.zeros((S, max_pages), np.int32)
    cu = np.zeros(S + 1, np.int32)
    kv_lens = np.zeros(S, np.int32)
    next_page = 1
    off = 0
    for i, (q_len, kv_len) in enumerate(seqs):
        n = -(-kv_len // page)
        pt[i, :n] = np.arange(next_page, next_page + n)
        next_page += n
        kv_lens[i] = kv_len
        off += q_len
        cu[i + 1] = off
    cu[len(seqs) + 1:] = off
    assert next_page <= num_pages
    q = rng.standard_normal((T, Hq, D)).astype(np.float32)
    md = AttentionMetadata(
        cu_q_lens=jnp.asarray(cu), kv_lens=jnp.asarray(kv_lens),
        page_table=jnp.asarray(pt),
        num_seqs=jnp.asarray(len(seqs), jnp.int32))
    return q, k_cache, v_cache, md


CASES = [
    # single prefill
    dict(seqs=[(12, 12)], Hq=4, Hkv=2, D=64, page=4, pages=8),
    # chunked prefill: new chunk attends to prior cached context
    dict(seqs=[(8, 29)], Hq=4, Hkv=2, D=64, page=4, pages=12),
    # mixed: decode rows + prefill chunks, unsorted sizes
    dict(seqs=[(1, 17), (9, 9), (1, 5), (13, 20)], Hq=8, Hkv=2, D=64,
         page=8, pages=16),
    # many decode rows spanning a q block + one prefill
    dict(seqs=[(1, 3)] * 7 + [(21, 21)], Hq=4, Hkv=4, D=32, page=4,
         pages=24),
    # padded seq rows at the tail (cu repeats, kv_len 0)
    dict(seqs=[(6, 6), (1, 9)], pad_seqs=3, Hq=4, Hkv=1, D=64, page=4,
         pages=8),
    # MQA with distinct v_dim exercised separately below
]


@pytest.mark.parametrize("case", CASES)
def test_matches_xla_oracle(case):
    rng = np.random.default_rng(7)
    case = dict(case)
    pad_seqs = case.pop("pad_seqs", 0)
    q, kc, vc, md = build_case(rng, case["seqs"], case["Hq"], case["Hkv"],
                               case["D"], case["page"], case["pages"],
                               pad_seqs)
    scale = case["D"] ** -0.5
    max_q = max(ql for ql, _ in case["seqs"])
    want = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kc),
                                jnp.asarray(vc), md, scale=scale,
                                max_q_len=max_q)
    got = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=scale, q_block=8, kv_block=16,
        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    assert not np.isnan(np.asarray(got)).any()


def test_q_block_spanning_many_seqs():
    """One q block covering several sequences (the decode-heavy mixed case):
    per-row online-softmax state must not leak across seq boundaries."""
    rng = np.random.default_rng(3)
    seqs = [(1, k) for k in [3, 9, 1, 14, 6, 2, 30, 8]] + [(5, 5)]
    q, kc, vc, md = build_case(rng, seqs, 4, 2, 32, 4, 32)
    scale = 0.2
    want = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kc),
                                jnp.asarray(vc), md, scale=scale,
                                max_q_len=5)
    got = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=scale, q_block=16, kv_block=8,
        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_long_context_online_softmax():
    rng = np.random.default_rng(11)
    q, kc, vc, md = build_case(rng, [(4, 260)], 4, 2, 64, 8, 40)
    scale = 0.125
    want = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kc),
                                jnp.asarray(vc), md, scale=scale,
                                max_q_len=4)
    got = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), md.cu_q_lens,
        md.kv_lens, md.page_table, scale=scale, q_block=4, kv_block=16,
        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_distinct_v_dim_mla_layout():
    """Values as the latent prefix of keys (MLA absorbed layout: Dv < D)."""
    rng = np.random.default_rng(5)
    Hq, D, Dv, page, num_pages = 4, 64, 32, 4, 16
    seqs = [(6, 13), (1, 8)]
    S = len(seqs)
    T = sum(q for q, _ in seqs)
    k_cache = rng.standard_normal((num_pages, page, 1, D)).astype(np.float32)
    v_cache = k_cache[..., :Dv].copy()
    max_pages = 4
    pt = np.zeros((S, max_pages), np.int32)
    cu = np.zeros(S + 1, np.int32)
    kv_lens = np.zeros(S, np.int32)
    next_page, off = 1, 0
    for i, (ql, kv) in enumerate(seqs):
        n = -(-kv // page)
        pt[i, :n] = np.arange(next_page, next_page + n)
        next_page += n
        kv_lens[i] = kv
        off += ql
        cu[i + 1] = off
    q = rng.standard_normal((T, Hq, D)).astype(np.float32)
    md = AttentionMetadata(cu_q_lens=jnp.asarray(cu),
                           kv_lens=jnp.asarray(kv_lens),
                           page_table=jnp.asarray(pt),
                           num_seqs=jnp.asarray(S, jnp.int32))
    scale = D ** -0.5
    want = _xla_paged_attention(jnp.asarray(q), jnp.asarray(k_cache),
                                jnp.asarray(v_cache), md, scale=scale,
                                max_q_len=6)
    got = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        md.cu_q_lens, md.kv_lens, md.page_table, scale=scale, q_block=8,
        kv_block=8, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_engine_e2e_with_pallas_mixed(tmp_path):
    """Full engine with attention_impl='pallas': prefill now runs the
    ragged kernel (interpret on CPU); output must match the xla impl."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams

    torch.manual_seed(9)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=128, eos_token_id=0, attention_bias=False))
    model.save_pretrained(tmp_path, safe_serialization=True)

    prompts = [[5, 9, 23, 40, 2, 71, 33], [8, 1], [99, 98, 97, 96, 95, 94,
                                                   93, 92, 91, 90, 89, 88]]

    def run(impl):
        cfg = EngineConfig(
            model=str(tmp_path), dtype="float32", max_model_len=64,
            attention_impl=impl,
            scheduler=SchedulerConfig(max_prefill_tokens=8,
                                      min_prefill_tokens=4),
            cache=CacheConfig(page_size=4, num_pages=64))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=prompts,
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))]

    assert run("pallas") == run("xla")
