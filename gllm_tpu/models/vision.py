"""Qwen2.5-VL vision tower (ViT + window attention + patch merger).

TPU-native re-design of the reference vision transformer
(/root/reference/gllm/models/qwen2_5_vl.py:139-697):

- **Functional, stacked params**: block weights stacked on a leading
  [depth] axis; the block loop is a Python loop (per-layer full/window
  switch) with static slicing into the stack.
- **Window layers run batched padded-window attention**: tokens (already
  permuted into window order) are gathered into a [num_windows, Wmax]
  lattice — one uniform batched MXU matmul, memory and compute linear in
  image size (the reference gets this from flash varlen attention).
- **Full-attention layers** (a handful per tower) run per-frame-masked
  global attention, q-chunked via ``lax.map`` above a size threshold so the
  transient score tensor is O(L·chunk), never O(L²).
- **Host precompute per grid**: window permutation, gather lattice, frame
  segment ids and 2-D rotary tables are pure functions of (t, h, w) —
  computed once per grid in numpy and lru-cached (reference get_rope_by_thw
  does the same).

Weight layout is [in, out] (x @ W) like the LM modules.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gllm_tpu.ops import rms_norm

Params = Dict[str, Any]

# Full-attention score tensors are materialized dense below this many
# tokens; above it the q axis is chunked (exact, two-matmul-per-chunk).
_FULL_DENSE_MAX = 2048
_FULL_CHUNK = 128


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    depth: int
    hidden_size: int
    intermediate_size: int
    num_heads: int
    patch_size: int
    temporal_patch_size: int
    in_channels: int
    spatial_merge_size: int
    out_hidden_size: int
    window_size: int
    fullatt_block_indexes: Tuple[int, ...]
    rms_norm_eps: float = 1e-6

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def merge_unit(self) -> int:
        return self.spatial_merge_size ** 2

    @property
    def patch_input_dim(self) -> int:
        return (self.in_channels * self.temporal_patch_size
                * self.patch_size ** 2)


def from_hf_vision_config(d: Dict[str, Any]) -> VisionConfig:
    return VisionConfig(
        depth=d.get("depth", 32),
        hidden_size=d.get("hidden_size", 1280),
        intermediate_size=d.get("intermediate_size", 3420),
        num_heads=d.get("num_heads", 16),
        patch_size=d.get("patch_size", 14),
        temporal_patch_size=d.get("temporal_patch_size", 2),
        in_channels=d.get("in_channels", 3),
        spatial_merge_size=d.get("spatial_merge_size", 2),
        out_hidden_size=d.get("out_hidden_size", 3584),
        window_size=d.get("window_size", 112),
        fullatt_block_indexes=tuple(
            d.get("fullatt_block_indexes", (7, 15, 23, 31))),
    )


def init_vision_params(cfg: VisionConfig, seed: int = 0,
                       dtype=jnp.float32) -> Params:
    L, H, I = cfg.depth, cfg.hidden_size, cfg.intermediate_size
    mu, out = cfg.merge_unit, cfg.out_hidden_size
    key = jax.random.key(seed + 7)
    ks = iter(jax.random.split(key, 16))

    def w(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32)
                * scale).astype(dtype)

    s = H ** -0.5
    return {
        "patch_embed": w(next(ks), (cfg.patch_input_dim, H),
                         cfg.patch_input_dim ** -0.5),
        "blocks": {
            "norm1": jnp.ones((L, H), dtype),
            "norm2": jnp.ones((L, H), dtype),
            "qkv_w": w(next(ks), (L, H, 3 * H), s),
            "qkv_b": jnp.zeros((L, 3 * H), dtype),
            "proj_w": w(next(ks), (L, H, H), s),
            "proj_b": jnp.zeros((L, H), dtype),
            "gate_w": w(next(ks), (L, H, I), s),
            "gate_b": jnp.zeros((L, I), dtype),
            "up_w": w(next(ks), (L, H, I), s),
            "up_b": jnp.zeros((L, I), dtype),
            "down_w": w(next(ks), (L, I, H), I ** -0.5),
            "down_b": jnp.zeros((L, H), dtype),
        },
        "merger": {
            "ln_q": jnp.ones((H,), dtype),
            "fc1_w": w(next(ks), (mu * H, mu * H), (mu * H) ** -0.5),
            "fc1_b": jnp.zeros((mu * H,), dtype),
            "fc2_w": w(next(ks), (mu * H, out), (mu * H) ** -0.5),
            "fc2_b": jnp.zeros((out,), dtype),
        },
    }


# ---------------------------------------------------------------------------
# Host precompute per (t, h, w) grid
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=512)
def _grid_precompute(t: int, h: int, w: int, window_size: int,
                     patch_size: int, merge: int, head_dim: int):
    """Per-grid static data, all in the PERMUTED (window) token order:

    (window_index [L/mu], reverse_index [L/mu], seg_full [L],
     win_gather [NW, Wmax] int32 with pad sentinel L, cos/sin [L, head_dim])

    Port of the reference's get_window_index_thw / rotary_pos_emb_thw
    semantics (qwen2_5_vl.py:502-589).
    """
    lh, lw = h // merge, w // merge
    mu = merge * merge
    L = t * h * w
    win = window_size // merge // patch_size     # merger-window side

    index = np.arange(t * lh * lw).reshape(t, lh, lw)
    pad_h = (-lh) % win
    pad_w = (-lw) % win
    index_p = np.pad(index, ((0, 0), (0, pad_h), (0, pad_w)),
                     constant_values=-100)
    nwh, nww = (lh + pad_h) // win, (lw + pad_w) // win
    index_p = index_p.reshape(t, nwh, win, nww, win) \
                     .transpose(0, 1, 3, 2, 4).reshape(t, nwh * nww, win,
                                                       win)
    seqlens = (index_p != -100).sum(axis=(2, 3)).reshape(-1)
    flat = index_p.reshape(-1)
    window_index = flat[flat != -100]                       # [t*lh*lw]
    # token-granular window sizes (permuted order is window-contiguous)
    win_sizes = seqlens[seqlens > 0] * mu
    wmax = win * win * mu
    nw = len(win_sizes)
    win_gather = np.full((nw, wmax), L, np.int64)
    pos = 0
    for i, n in enumerate(win_sizes):
        win_gather[i, :n] = np.arange(pos, pos + n)
        pos += n
    assert pos == L
    # full attention = per-frame segments; permuted unit u belongs to frame
    # window_index[u] // (lh*lw)
    seg_full = np.repeat(window_index // (lh * lw), mu)     # [L]

    # 2-D rotary in ORIGINAL order, then permuted (reference
    # rotary_pos_emb_thw then [window_index] gather).
    hpos = np.broadcast_to(np.arange(h)[:, None], (h, w))
    wpos = np.broadcast_to(np.arange(w)[None, :], (h, w))

    def merge_order(p):
        return p.reshape(h // merge, merge, w // merge, merge) \
                .transpose(0, 2, 1, 3).reshape(-1)

    hpos = np.tile(merge_order(hpos), t)                    # [L]
    wpos = np.tile(merge_order(wpos), t)
    inv_freq = 1.0 / (10000.0 ** (np.arange(0, head_dim // 2, 2,
                                            dtype=np.float64)
                                  / (head_dim // 2)))
    freqs = np.concatenate([hpos[:, None] * inv_freq[None, :],
                            wpos[:, None] * inv_freq[None, :]],
                           axis=-1)                         # [L, head_dim/2]
    # permute freqs into window order (unit granularity)
    freqs = freqs.reshape(L // mu, mu, -1)[window_index].reshape(L, -1)
    emb = np.concatenate([freqs, freqs], axis=-1)           # [L, head_dim]
    reverse_index = np.argsort(window_index)
    return (window_index.astype(np.int32), reverse_index.astype(np.int32),
            seg_full.astype(np.int32), win_gather.astype(np.int32),
            np.cos(emb).astype(np.float32), np.sin(emb).astype(np.float32))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _rope(a, cos, sin):
    """HF apply_rotary_pos_emb_vision: rotate-half over the full head dim.
    a: [..., nh, hd]; cos/sin: [..., hd] broadcast over heads."""
    hd = a.shape[-1]
    af = a.astype(jnp.float32)
    half = jnp.concatenate([-af[..., hd // 2:], af[..., :hd // 2]],
                           axis=-1)
    return (af * cos[..., None, :] + half * sin[..., None, :]).astype(
        a.dtype)


def _qkv(bp, x, cfg):
    nh, hd = cfg.num_heads, cfg.head_dim
    qkv = x @ bp["qkv_w"] + bp["qkv_b"]
    return [a.reshape(*x.shape[:-1], nh, hd)
            for a in jnp.split(qkv, 3, axis=-1)]


def _window_attention(bp, x, cos, sin, win_gather, cfg: VisionConfig):
    """Batched padded-window attention: x [L, H] gathered into
    [NW, Wmax, H]; pad slots point at a zero sentinel row L and are masked
    out of the softmax."""
    L, H = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    valid = win_gather < L                              # [NW, Wmax]
    pad_row = jnp.zeros((1, H), x.dtype)
    xw = jnp.concatenate([x, pad_row])[win_gather]      # [NW, Wmax, H]
    cosw = jnp.concatenate([cos, jnp.zeros((1, hd))])[win_gather]
    sinw = jnp.concatenate([sin, jnp.zeros((1, hd))])[win_gather]
    q, k, v = _qkv(bp, xw, cfg)                         # [NW, Wmax, nh, hd]
    q, k = _rope(q, cosw, sinw), _rope(k, cosw, sinw)
    scores = jnp.einsum("wqhd,wkhd->whqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * hd ** -0.5
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("whqk,wkhd->wqhd", probs, v.astype(jnp.float32))
    out = out.reshape(-1, H).astype(x.dtype)
    # scatter back (each real token appears exactly once; pads land on the
    # dropped sentinel row)
    flat = jnp.zeros((L + 1, H), x.dtype).at[win_gather.reshape(-1)].set(out)
    return flat[:L] @ bp["proj_w"] + bp["proj_b"]


def _full_attention(bp, x, cos, sin, seg, cfg: VisionConfig):
    """Global attention masked to frame segments; q-chunked above
    _FULL_DENSE_MAX tokens so score memory is O(L·chunk)."""
    L, H = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    q, k, v = _qkv(bp, x, cfg)                          # [L, nh, hd]
    q, k = _rope(q, cos, sin), _rope(k, cos, sin)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    def attend(qb, segb):
        # qb [B, nh, hd], segb [B] → [B, nh, hd]
        scores = jnp.einsum("qhd,khd->hqk", qb.astype(jnp.float32),
                            kf) * hd ** -0.5
        mask = segb[:, None] == seg[None, :]
        scores = jnp.where(mask[None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("hqk,khd->qhd", probs, vf)

    if L <= _FULL_DENSE_MAX:
        out = attend(q, seg)
    else:
        pad = (-L) % _FULL_CHUNK
        qp = jnp.pad(q, ((0, pad), (0, 0), (0, 0)))
        segp = jnp.pad(seg, (0, pad), constant_values=-1)
        nb = qp.shape[0] // _FULL_CHUNK
        out = jax.lax.map(
            lambda args: attend(*args),
            (qp.reshape(nb, _FULL_CHUNK, nh, hd),
             segp.reshape(nb, _FULL_CHUNK)))
        out = out.reshape(-1, nh, hd)[:L]
    out = out.reshape(L, H).astype(x.dtype)
    return out @ bp["proj_w"] + bp["proj_b"]


def _vit_jit(params, pixels, cos, sin, seg_full, win_gather, window_index,
             reverse_index, cfg: VisionConfig):
    mu = cfg.merge_unit
    x = pixels @ params["patch_embed"]                     # [L, H]
    L = x.shape[0]
    x = x.reshape(L // mu, mu, -1)[window_index].reshape(L, -1)

    for i in range(cfg.depth):
        bp = jax.tree.map(lambda a: a[i], params["blocks"])
        h = rms_norm(x, bp["norm1"], cfg.rms_norm_eps)
        if i in cfg.fullatt_block_indexes:
            x = x + _full_attention(bp, h, cos, sin, seg_full, cfg)
        else:
            x = x + _window_attention(bp, h, cos, sin, win_gather, cfg)
        h = rms_norm(x, bp["norm2"], cfg.rms_norm_eps)
        gate = h @ bp["gate_w"] + bp["gate_b"]
        up = h @ bp["up_w"] + bp["up_b"]
        x = x + (jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype)
                 * up) @ bp["down_w"] + bp["down_b"]

    m = params["merger"]
    x = rms_norm(x, m["ln_q"], cfg.rms_norm_eps).reshape(L // mu, -1)
    x = x @ m["fc1_w"] + m["fc1_b"]
    x = (jax.nn.gelu(x.astype(jnp.float32), approximate=False)
         .astype(x.dtype))
    x = x @ m["fc2_w"] + m["fc2_b"]
    return x[reverse_index]                                # [L/mu, out]


_vit_jit = jax.jit(_vit_jit, static_argnames=("cfg",))


def embed_single(params: Params, cfg: VisionConfig, pixels,
                 grid_thw: Tuple[int, int, int]) -> jnp.ndarray:
    """One image/video item: pixels [t*h*w, C*tps*ps*ps] (the HF processor's
    flattened patch layout) → merged visual embeddings [t*h*w/mu, out]."""
    t, h, w = (int(v) for v in grid_thw)
    window_index, reverse_index, seg_full, win_gather, cos, sin = \
        _grid_precompute(t, h, w, cfg.window_size, cfg.patch_size,
                         cfg.spatial_merge_size, cfg.head_dim)
    return _vit_jit(params, jnp.asarray(pixels), jnp.asarray(cos),
                    jnp.asarray(sin), jnp.asarray(seg_full),
                    jnp.asarray(win_gather), jnp.asarray(window_index),
                    jnp.asarray(reverse_index), cfg)
