"""MMLU-Pro-style multiple-choice accuracy eval against a running server
(reference benchmarks/evaluate_mmlu_pro.py).

Zero-egress environment: the dataset must be a LOCAL file
(``--data-path`` jsonl with fields: question, options (list), answer
(letter or index)). The prompting/extraction protocol mirrors the
reference: few-shot-free direct answering, "Answer:" extraction of the
first choice letter.
"""

import argparse
import http.client
import json
import re
import sys

LETTERS = "ABCDEFGHIJ"


def format_prompt(q):
    opts = "\n".join(f"{LETTERS[i]}. {o}"
                     for i, o in enumerate(q["options"]))
    return (f"Question: {q['question']}\nOptions:\n{opts}\n"
            "Answer with the option letter only.\nAnswer:")


def extract_choice(text):
    """Same priority ladder as evaluate_mmmu.py: explicit "answer is X",
    reply leading with the letter, then standalone capitals excluding the
    English words "I"/"A"."""
    t = (text or "").strip()
    m = re.search(r"answer\s*(?:is|:)?\s*\*{0,2}\(?([A-Ja-j])\b", t,
                  re.IGNORECASE)
    if m:
        return m.group(1).upper()
    m = re.match(r"\(?([A-Ja-j])\)?(?:[.,:)]|$)", t)
    if m:
        return m.group(1).upper()
    # leading letter + space: plausible for "B because ..." but not for
    # the English words "I ..." / "A ..."
    m = re.match(r"([B-HJb-hj])\s", t)
    if m:
        return m.group(1).upper()
    m = re.search(r"\b([B-HJ])\b", t)
    return m.group(1) if m else None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-path", required=True,
                    help="local jsonl: question/options/answer per line")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--limit", type=int, default=None)
    args = ap.parse_args()

    with open(args.data_path) as f:
        questions = [json.loads(line) for line in f if line.strip()]
    if args.limit:
        questions = questions[:args.limit]

    correct = total = 0
    for q in questions:
        body = {"messages": [{"role": "user",
                              "content": format_prompt(q)}],
                "max_tokens": 8, "temperature": 0.0}
        conn = http.client.HTTPConnection(args.host, args.port, timeout=600)
        conn.request("POST", "/v1/chat/completions", body=json.dumps(body),
                     headers={"Content-Type": "application/json"})
        d = json.loads(conn.getresponse().read())
        conn.close()
        got = extract_choice(d["choices"][0]["message"]["content"] or "")
        want = q["answer"]
        if isinstance(want, int):
            want = LETTERS[want]
        total += 1
        correct += int(got == str(want).strip().upper())
        if total % 50 == 0:
            print(f"{total}: acc={correct / total:.3f}", file=sys.stderr)
    print(json.dumps({"metric": "mmlu_pro_accuracy",
                      "value": correct / max(1, total),
                      "n": total}))


if __name__ == "__main__":
    main()
