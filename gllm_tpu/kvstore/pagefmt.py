"""Content-addressed prefix-page format shared by the disk and peer tiers.

One *page payload* is the self-describing serialization of one host-pool
page — every KV leaf's per-page slab — plus the metadata the lower tiers
need to stay exactly as safe as the host tier they extend:

- the **chained digest** (the content address; same
  ``memory_manager.prefix_digests`` chain the HBM and host tiers key by),
- the **8-token canary** (same collision guard: a reader verifies the
  canary against the tokens it is probing for and treats any mismatch as
  a poisoned miss),
- the **parent digest** (the previous page in the chain — the disk
  tier's read-ahead walks this edge to prefetch descendants),
- the **geometry**: per-leaf shapes and dtypes plus the page size. A
  payload written by an int8-KV replica is half the bytes of a bf16 one
  and *must not* be restored into a bf16 pool — geometry mismatch is a
  hard miss, which is what the peer protocol's hello negotiation checks
  up front.

Layout: ``u32 header_len | header JSON (utf-8) | leaf bytes...`` with
leaves concatenated in pool order, C-contiguous. Stdlib + numpy only —
no jax, no pickle (payloads cross trust boundaries: a peer fetch must
never execute remote bytes).
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_HLEN = struct.Struct("!I")
FORMAT_VERSION = 1


def pool_geometry(page_shapes: Sequence[Tuple[tuple, np.dtype]],
                  page_size: int) -> dict:
    """Canonical geometry dict for a ``HostKVPool``-shaped page layout.
    Two stores interoperate iff their geometries compare equal — this is
    the negotiated object of the peer hello exchange."""
    return {
        "v": FORMAT_VERSION,
        "page_size": int(page_size),
        "leaves": [[list(int(x) for x in s), np.dtype(d).name]
                   for s, d in page_shapes],
    }


def geometry_bytes(geometry: dict) -> int:
    """Payload bytes one page of this geometry serializes to (leaves
    only; the header adds ~200 B)."""
    return sum(int(np.prod(s)) * np.dtype(d).itemsize
               for s, d in geometry["leaves"])


def pack_header(digest: bytes, canary: Sequence[int],
                parent: Optional[bytes], geometry: dict) -> bytes:
    """The ``[u32 len][header JSON]`` prefix of a payload — cheap (no
    leaf bytes touched), so hot paths can compute exact payload sizes
    and defer the leaf serialization to a worker."""
    header = dict(geometry)
    header["digest"] = digest.hex()
    header["canary"] = [int(c) for c in canary]
    header["parent"] = parent.hex() if parent else ""
    hdr = json.dumps(header, separators=(",", ":")).encode()
    return _HLEN.pack(len(hdr)) + hdr


def coerce_leaves(leaves: Sequence[np.ndarray],
                  geometry: dict) -> List[np.ndarray]:
    """Validate leaves against the geometry and make them contiguous in
    the right dtype (a no-op for pool slabs, which already match)."""
    out = []
    for leaf, (shape, dtype) in zip(leaves, geometry["leaves"]):
        arr = np.ascontiguousarray(leaf, dtype=np.dtype(dtype))
        if list(arr.shape) != list(shape):
            raise ValueError(
                f"leaf shape {arr.shape} does not match geometry {shape}")
        out.append(arr)
    return out


def assemble_payload(header_prefix: bytes,
                     leaves: Sequence[np.ndarray]) -> bytes:
    return header_prefix + b"".join(leaf.tobytes() for leaf in leaves)


def pack_page(digest: bytes, canary: Sequence[int],
              parent: Optional[bytes], leaves: Sequence[np.ndarray],
              geometry: dict) -> bytes:
    return assemble_payload(pack_header(digest, canary, parent, geometry),
                            coerce_leaves(leaves, geometry))


def read_header(payload: bytes) -> dict:
    """Header dict of a packed payload (no leaf deserialization)."""
    if len(payload) < _HLEN.size:
        raise ValueError("truncated page payload")
    (hlen,) = _HLEN.unpack_from(payload)
    if len(payload) < _HLEN.size + hlen:
        raise ValueError("truncated page header")
    return json.loads(payload[_HLEN.size:_HLEN.size + hlen].decode())


def unpack_page(payload: bytes, geometry: dict
                ) -> Tuple[dict, List[np.ndarray]]:
    """Parse a payload and verify it against the LOCAL geometry.

    Returns ``(header, leaves)``. Raises ``ValueError`` on any
    structural mismatch — truncation, wrong leaf set, wrong dtype/shape,
    wrong page size — so a caller can only ever restore bytes that mean
    the same thing locally that they meant to the writer.
    """
    header = read_header(payload)
    if (header.get("v") != geometry["v"]
            or header.get("page_size") != geometry["page_size"]
            or header.get("leaves") != geometry["leaves"]):
        raise ValueError(
            f"page geometry mismatch: payload "
            f"{ {k: header.get(k) for k in ('v', 'page_size')} } vs local "
            f"{ {k: geometry[k] for k in ('v', 'page_size')} }")
    (hlen,) = _HLEN.unpack_from(payload)
    off = _HLEN.size + hlen
    leaves: List[np.ndarray] = []
    for shape, dtype in geometry["leaves"]:
        dt = np.dtype(dtype)
        n = int(np.prod(shape)) * dt.itemsize
        if off + n > len(payload):
            raise ValueError("truncated page payload (leaf bytes)")
        leaves.append(np.frombuffer(payload, dtype=dt, count=int(np.prod(shape)),
                                    offset=off).reshape(shape))
        off += n
    if off != len(payload):
        raise ValueError("trailing bytes after page payload")
    return header, leaves


def header_meta(header: dict) -> Tuple[bytes, Tuple[int, ...],
                                       Optional[bytes]]:
    """(digest, canary, parent) out of a parsed header."""
    parent = bytes.fromhex(header["parent"]) if header.get("parent") \
        else None
    return (bytes.fromhex(header["digest"]),
            tuple(int(c) for c in header["canary"]), parent)


def verify_payload(payload: bytes, geometry: dict, digest: bytes,
                   tokens, mangle_canary: bool = False
                   ) -> Tuple[List[np.ndarray], Optional[bytes]]:
    """THE verification gate every lower tier reads through: unpack
    against the local geometry, then require the header's digest to be
    the probed digest and its canary to match the probed tokens. Raises
    ``ValueError`` on any mismatch — one implementation, so the disk
    and peer tiers can never drift on what counts as trustworthy.
    ``mangle_canary`` is the ``disk_read_corrupt`` chaos hook: it
    simulates bit-rot AFTER unpack so the canary check must be what
    catches it. Returns contiguous leaf COPIES (safe to write into pool
    storage) plus the chain parent."""
    header, leaves = unpack_page(payload, geometry)
    got_digest, canary, parent = header_meta(header)
    if mangle_canary:
        canary = tuple(int(c) + 1 for c in canary)
    if got_digest != digest:
        raise ValueError("payload digest mismatch")
    if tuple(tokens[:len(canary)]) != tuple(canary):
        raise ValueError("payload canary mismatch")
    return [np.array(leaf) for leaf in leaves], parent
