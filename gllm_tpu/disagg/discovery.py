"""Dynamic discovery registry with TTL leases.

Re-design of the reference's zmq DEALER/ROUTER registry
(/root/reference/gllm/disagg/discovery.py): encoder and LM servers are
decoupled processes that find each other via a shared registry. Each side
``publish``-es its role payload (control address, feat_dim, processor-config
hash) and ``poll_events``-es the peer role for ADD/UPDATE/REMOVE diffs:

* either side may start first (publish + watch are symmetric);
* a killed member's lease expires → peers see REMOVE and drop it;
* a restarted member re-publishes → ADD and reconnect;
* processor-config mismatches are rejected at connect time.

Transport is the stdlib framed-TCP server (gllm_tpu/disagg/wire.py);
publishers renew every ttl/3, the server reaps stale leases on read.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from gllm_tpu.disagg.wire import MsgServer, connect, recv_msg, send_msg


@dataclass
class Event:
    kind: str          # "ADD" | "UPDATE" | "REMOVE"
    identity: str
    payload: dict


def make_payload(*, role: str, addr: str, feat_dim: int = 0,
                 processor_config_hash: str = "",
                 extra: Optional[dict] = None) -> dict:
    """Discovery payload for one member: ``addr`` is the member's control
    endpoint ("host:port" of its job/meta server)."""
    return {"role": role, "addr": addr, "feat_dim": int(feat_dim),
            "processor_config_hash": processor_config_hash,
            "extra": extra or {}}


class DiscoveryServer:
    """The standalone registry process (reference DiscoveryServer).

    State: {identity: (payload, version, lease_deadline)}. Requests:
      ("publish", identity, payload, ttl_ms) → ("ok",)
      ("renew", identity)                    → ("ok"|"unknown",)
      ("revoke", identity)                   → ("ok",)
      ("list", role)                         → ("ok", {identity: (payload,
                                                version)})
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 default_ttl_ms: float = 3000.0):
        self._members: Dict[str, Tuple[dict, int, float, float]] = {}
        self._lock = threading.Lock()
        self.default_ttl_ms = default_ttl_ms
        self._server = MsgServer(host, port, self._handle)
        self.port = self._server.port

    def start(self) -> "DiscoveryServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop()

    def _reap(self, now: float) -> None:
        dead = [k for k, (_, _, _, dl) in self._members.items() if now > dl]
        for k in dead:
            del self._members[k]

    def _handle(self, msg, sock) -> None:
        kind = msg[0]
        now = time.monotonic() * 1000.0
        with self._lock:
            self._reap(now)
            if kind == "publish":
                _, identity, payload, ttl_ms = msg
                ttl = ttl_ms or self.default_ttl_ms
                old = self._members.get(identity)
                version = (old[1] + 1) if old else 1
                self._members[identity] = (payload, version, ttl, now + ttl)
                send_msg(sock, ("ok",))
            elif kind == "renew":
                _, identity = msg
                m = self._members.get(identity)
                if m is None:
                    send_msg(sock, ("unknown",))
                else:
                    payload, version, ttl, _ = m
                    self._members[identity] = (payload, version, ttl,
                                               now + ttl)
                    send_msg(sock, ("ok",))
            elif kind == "revoke":
                _, identity = msg
                self._members.pop(identity, None)
                send_msg(sock, ("ok",))
            elif kind == "list":
                _, role = msg
                out = {k: (p, v) for k, (p, v, _, _) in
                       self._members.items() if p.get("role") == role}
                send_msg(sock, ("ok", out))
            else:
                send_msg(sock, ("error", f"unknown request {kind!r}"))


def serve_discovery(host: str = "0.0.0.0", port: int = 7606) -> None:
    """Blocking entrypoint for a standalone registry (reference
    discovery_server.py)."""
    srv = DiscoveryServer(host, port).start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


class NetworkDiscovery:
    """Client: publish-with-renewal + poll_events diffing for one watched
    role (reference NetworkDiscovery)."""

    def __init__(self, endpoint: str, ttl_ms: float = 3000.0):
        host, _, port = endpoint.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.ttl_ms = ttl_ms
        self._lock = threading.Lock()
        self._sock = None
        self._published: Dict[str, dict] = {}
        self._seen: Dict[str, Tuple[dict, int]] = {}
        self._renew_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _request(self, msg):
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._sock = connect(self._addr)
                    send_msg(self._sock, msg)
                    out = recv_msg(self._sock)
                    if out is None:
                        raise ConnectionError("registry EOF")
                    return out
                except (ConnectionError, OSError):
                    try:
                        if self._sock is not None:
                            self._sock.close()
                    finally:
                        self._sock = None
                    if attempt:
                        raise
            return None

    def publish(self, identity: str, payload: dict) -> None:
        self._request(("publish", identity, payload, self.ttl_ms))
        self._published[identity] = payload
        if self._renew_thread is None:
            self._renew_thread = threading.Thread(target=self._renew_loop,
                                                  daemon=True)
            self._renew_thread.start()

    def revoke(self, identity: str) -> None:
        self._published.pop(identity, None)
        try:
            self._request(("revoke", identity))
        except (ConnectionError, OSError):
            pass

    def _renew_loop(self) -> None:
        while not self._stop.wait(self.ttl_ms / 3000.0):
            for identity, payload in list(self._published.items()):
                try:
                    out = self._request(("renew", identity))
                    if out and out[0] == "unknown":
                        # registry restarted → re-publish
                        self._request(("publish", identity, payload,
                                       self.ttl_ms))
                except (ConnectionError, OSError):
                    pass  # registry down; retry next tick

    def list(self, role: str) -> Dict[str, dict]:
        out = self._request(("list", role))
        return {k: p for k, (p, _) in out[1].items()} if out else {}

    def poll_events(self, role: str) -> List[Event]:
        """Diff the registry's view of ``role`` against what we've seen."""
        try:
            out = self._request(("list", role))
        except (ConnectionError, OSError):
            return []
        if not out or out[0] != "ok":
            return []
        current: Dict[str, Tuple[dict, int]] = out[1]
        events: List[Event] = []
        for identity, (payload, version) in current.items():
            seen = self._seen.get(identity)
            if seen is None:
                events.append(Event("ADD", identity, payload))
            elif seen[1] != version:
                events.append(Event("UPDATE", identity, payload))
        for identity, (payload, _) in list(self._seen.items()):
            if identity not in current:
                events.append(Event("REMOVE", identity, payload))
        self._seen = dict(current)
        return events

    def close(self) -> None:
        self._stop.set()
        for identity in list(self._published):
            self.revoke(identity)
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
