"""Host-RAM KV offload tier (gllm_tpu/kvswap).

Three layers of coverage, all CPU-deterministic:

- HostKVPool unit semantics (free list, LRU eviction, pinning, canary);
- scheduler-level swap flows against a real KVSwapManager + fake model
  loop (swap-out on preemption, swap-in at re-admission, pool-full
  fallback, abort releasing host pages, zero re-prefill accounting);
- engine e2e: preempt-swap-resume is TOKEN-IDENTICAL to uninterrupted
  decode, every preemption resumes via swap-in
  (gllm_kvswap_swap_in_total == gllm_sched_preemptions_total — the
  acceptance criterion), a disabled pool reproduces recompute behavior,
  and host-tier prefix restore is digest-verified end to end.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.kvswap import HostKVPool, KVSwapManager
from gllm_tpu.memory_manager import make_memory_manager
from gllm_tpu.obs import metrics as obs
from gllm_tpu.sampling_params import SamplingParams
from gllm_tpu.scheduler import Scheduler
from gllm_tpu.sequence import Sequence, SequenceStatus

EOS = 2


# ---- HostKVPool unit semantics --------------------------------------------

def _pool(n=8):
    return HostKVPool([((2, 4, 3), np.float32), ((2, 4), np.int32)], n)


def test_pool_alloc_free_roundtrip():
    pool = _pool(4)
    pages = pool.allocate(3)
    assert sorted(pages) == [0, 1, 2] and pool.num_free == 1
    pool.free(pages)
    assert pool.num_free == 4
    with pytest.raises(RuntimeError):
        pool.free([0])            # double free


def test_pool_lru_eviction_prefers_oldest_unpinned():
    pool = _pool(3)
    pages = pool.allocate(3)
    for i, p in enumerate(pages):
        pool.put_prefix(p, bytes([i]), (i,))
    pool.pin([pages[0]])
    # full pool: allocating must evict the OLDEST UNPINNED prefix page
    got = pool.allocate(1)
    assert got == [pages[1]]
    assert pool.match_prefix(bytes([1]), (1,)) is None   # evicted
    assert pool.match_prefix(bytes([0]), (0,)) == pages[0]  # pinned kept
    # pinned pages alone can't be evicted
    pool.pin([pages[2]])
    assert pool.allocate(1) is None


def test_pool_canary_mismatch_is_poisoned_miss():
    pool = _pool()
    (p,) = pool.allocate(1)
    pool.put_prefix(p, b"d", (1, 2, 3))
    assert pool.match_prefix(b"d", [9, 9, 9]) is None      # collision
    # entry dropped: even the right canary misses now
    assert pool.match_prefix(b"d", [1, 2, 3]) is None


def test_pool_write_read_pages():
    pool = _pool()
    pages = pool.allocate(2)
    gathered = [np.arange(2 * 2 * 4 * 3, dtype=np.float32)
                .reshape(2, 2, 4, 3),
                np.arange(2 * 2 * 4, dtype=np.int32).reshape(2, 2, 4)]
    pool.write_page(pages[0], gathered, 0)
    pool.write_page(pages[1], gathered, 1)
    out = pool.read_pages(pages, pad_to=4)
    for leaf, src in zip(out, gathered):
        assert leaf.shape[1] == 4
        np.testing.assert_array_equal(leaf[:, :2], src)
        assert (np.asarray(leaf[:, 2:]) == 0).all()


# ---- scheduler-level flows ------------------------------------------------

def _kv_tree(num_pages, page_size):
    shape = (2, num_pages, page_size, 3)
    return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))


def make_swap_engine(num_pages=9, page_size=4, host_pages=32, maxp=32,
                     maxd=8, prefix=False):
    cfg = EngineConfig(
        max_model_len=num_pages * page_size,
        max_num_seqs=8,
        scheduler=SchedulerConfig(max_prefill_tokens=maxp,
                                  min_prefill_tokens=4,
                                  max_decode_seqs=maxd),
        cache=CacheConfig(page_size=page_size, num_pages=num_pages,
                          enable_prefix_caching=prefix,
                          kv_host_pool_pages=host_pages))
    mm = make_memory_manager(num_pages, page_size, prefix)
    kv = _kv_tree(num_pages, page_size)
    sw = KVSwapManager(kv, page_size, host_pages)
    mm.swap = sw
    return cfg, mm, sw, kv, Scheduler(cfg, mm)


def run_steps(sched, sw, kv, n_steps, sample_token=7):
    """Fake model loop: drain swap intents at 'dispatch' like the runner,
    then commit a constant sampled token."""
    for _ in range(n_steps):
        batch = sched.schedule_once()
        if batch is None:
            break
        kv = sw.apply(kv)
        sched.process_output(batch, [sample_token] * batch.num_seqs, EOS)
    return kv


def test_preemption_swaps_out_and_resumes_with_zero_reprefill():
    _, mm, sw, kv, sched = make_swap_engine()
    pre0 = obs.REGISTRY.get("gllm_sched_preemptions_total").get()
    out0 = obs.REGISTRY.get("gllm_kvswap_swap_out_total").get()
    in0 = obs.REGISTRY.get("gllm_kvswap_swap_in_total").get()
    a = Sequence(0, list(range(10, 14)), SamplingParams(max_tokens=16))
    b = Sequence(1, list(range(20, 24)), SamplingParams(max_tokens=16))
    sched.add_seq(a)
    sched.add_seq(b)
    frontier, violations = {}, []
    orig = sched.schedule_once

    def tracked():
        batch = orig()
        if batch is not None:
            for it in batch.items:
                f = frontier.get(it.seq.seq_id, 0)
                if it.computed_before < f:
                    violations.append((it.seq.seq_id, it.computed_before))
                frontier[it.seq.seq_id] = max(
                    f, it.computed_before + it.num_new_tokens)
        return batch

    sched.schedule_once = tracked
    kv = run_steps(sched, sw, kv, 80)
    assert a.status is SequenceStatus.FINISHED
    assert b.status is SequenceStatus.FINISHED
    pre = obs.REGISTRY.get("gllm_sched_preemptions_total").get() - pre0
    sout = obs.REGISTRY.get("gllm_kvswap_swap_out_total").get() - out0
    sin = obs.REGISTRY.get("gllm_kvswap_swap_in_total").get() - in0
    assert pre > 0, "workload did not create memory pressure"
    # every preemption swapped out and every victim resumed via swap-in:
    # zero re-prefill (the frontier tracker double-checks token-level)
    assert sout == pre and sin == pre
    assert not violations, violations
    # all device and host pages returned
    assert mm.num_free_pages == mm.allocator.num_total
    assert sw.pool.num_free == sw.pool.num_pages


def test_pool_full_falls_back_to_recompute():
    _, mm, sw, kv, sched = make_swap_engine(host_pages=1)
    fb0 = obs.REGISTRY.get("gllm_kvswap_recompute_fallbacks_total").get()
    a = Sequence(0, list(range(4)), SamplingParams(max_tokens=16))
    b = Sequence(1, list(range(4)), SamplingParams(max_tokens=16))
    sched.add_seq(a)
    sched.add_seq(b)
    kv = run_steps(sched, sw, kv, 80)
    assert a.status is SequenceStatus.FINISHED
    assert b.status is SequenceStatus.FINISHED
    fb = obs.REGISTRY.get("gllm_kvswap_recompute_fallbacks_total").get() - fb0
    assert fb > 0, "tiny host pool never forced the recompute fallback"
    assert sw.pool.num_free == sw.pool.num_pages


def test_abort_of_swapped_seq_releases_host_pages():
    _, mm, sw, kv, sched = make_swap_engine()
    a = Sequence(0, list(range(8)), SamplingParams(max_tokens=16))
    sched.add_seq(a)
    kv = run_steps(sched, sw, kv, 3)
    assert a.status is SequenceStatus.RUNNING
    # force a swap-out directly (the unit under test is the release path)
    sched.running.remove(a)
    assert sw.try_swap_out(a, mm)
    assert a.status is SequenceStatus.SWAPPED
    assert sw.pool.num_used > 0
    sched.waiting.appendleft(a)
    sched.abort_seq(0)
    sched.schedule_once()
    kv = sw.apply(kv)          # fetch lands; deferred frees resolve
    assert a.status is SequenceStatus.ABORTED
    assert sw.pool.num_free == sw.pool.num_pages
    assert mm.num_free_pages == mm.allocator.num_total


def test_host_pages_sizing():
    kv = _kv_tree(16, 4)
    per_page = 2 * (2 * 4 * 3) * 4          # two f32 leaves
    n = KVSwapManager.host_pages_for(kv, per_page * 10 / (1 << 30))
    assert n == 10


# ---- engine e2e (dummy-weight tiny model) ---------------------------------

MODEL_KW = dict(architecture="LlamaForCausalLM", vocab_size=512,
                hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                head_dim=16, intermediate_size=128, max_position=256)


def _make_llm(num_pages, host_pages, prefix=False, swap_policy="auto"):
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.models.config import ModelConfig
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=128,
        max_num_seqs=8,
        scheduler=SchedulerConfig(max_prefill_tokens=32,
                                  max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=num_pages,
                          enable_prefix_caching=prefix,
                          kv_host_pool_pages=host_pages,
                          swap_policy=swap_policy))
    return LLM(config=cfg, model_cfg=ModelConfig(**MODEL_KW))


def _workload(seed=0, n=4):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(1, 500, size=int(k)).tolist()
               for k in rng.integers(12, 28, size=n)]
    mk = lambda: [SamplingParams(temperature=0.0, max_tokens=20,  # noqa
                                 ignore_eos=True) for _ in prompts]
    return prompts, mk


@pytest.fixture(scope="module")
def reference_tokens():
    """Uninterrupted decode (ample pages, no tier) — ground truth."""
    prompts, mk = _workload()
    llm = _make_llm(num_pages=128, host_pages=None)
    outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                        sampling_params=mk())
    return [o.output_token_ids for o in outs]


def test_e2e_swap_resume_token_identical(reference_tokens):
    prompts, mk = _workload()
    pre0 = obs.REGISTRY.get("gllm_sched_preemptions_total").get()
    in0 = obs.REGISTRY.get("gllm_kvswap_swap_in_total").get()
    llm = _make_llm(num_pages=17, host_pages=64)
    assert llm.swap_manager is not None
    outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                        sampling_params=mk())
    pre = obs.REGISTRY.get("gllm_sched_preemptions_total").get() - pre0
    sin = obs.REGISTRY.get("gllm_kvswap_swap_in_total").get() - in0
    assert pre > 0, "no memory pressure — the test lost its teeth"
    # acceptance criterion: preempted seqs resume via swap-in, zero
    # re-prefill steps
    assert sin == pre
    assert [o.output_token_ids for o in outs] == reference_tokens
    sw = llm.swap_manager
    assert sw.pool.num_free == sw.pool.num_pages   # no host-page leak


def test_e2e_disabled_pool_reproduces_recompute(reference_tokens):
    prompts, mk = _workload()
    pre0 = obs.REGISTRY.get("gllm_sched_preemptions_total").get()
    out0 = obs.REGISTRY.get("gllm_kvswap_swap_out_total").get()
    llm = _make_llm(num_pages=17, host_pages=None)
    assert llm.swap_manager is None
    assert llm.memory_manager.swap is None
    outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                        sampling_params=mk())
    assert obs.REGISTRY.get("gllm_sched_preemptions_total").get() > pre0
    assert obs.REGISTRY.get("gllm_kvswap_swap_out_total").get() == out0
    # greedy decode: recompute must reproduce the same tokens
    assert [o.output_token_ids for o in outs] == reference_tokens


def test_e2e_swap_policy_recompute_disables_pool(reference_tokens):
    prompts, mk = _workload()
    llm = _make_llm(num_pages=17, host_pages=64, swap_policy="recompute")
    assert llm.swap_manager is None
    outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                        sampling_params=mk())
    assert [o.output_token_ids for o in outs] == reference_tokens


def test_e2e_prefix_spill_restore_digest_verified():
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, 500, size=40).tolist()
    sp = lambda: SamplingParams(temperature=0.0, max_tokens=8,  # noqa
                                ignore_eos=True)
    ref = _make_llm(num_pages=128, host_pages=None, prefix=True)
    want = ref.generate(prompt_token_ids=[list(prompt)],
                        sampling_params=sp())[0].output_token_ids

    llm = _make_llm(num_pages=40, host_pages=128, prefix=True)
    got1 = llm.generate(prompt_token_ids=[list(prompt)],
                        sampling_params=sp())[0].output_token_ids
    assert got1 == want
    # churn the HBM prefix cache until the prompt's pages are re-minted
    # (each re-mint spills the page host-side)
    for _ in range(6):
        filler = rng.integers(1, 500, size=60).tolist()
        llm.generate(prompt_token_ids=[filler], sampling_params=sp())
    spill = obs.REGISTRY.get(
        "gllm_kvswap_prefix_spill_pages_total").get()
    assert spill > 0
    rest0 = obs.REGISTRY.get(
        "gllm_kvswap_prefix_restore_pages_total").get()
    got2 = llm.generate(prompt_token_ids=[list(prompt)],
                        sampling_params=sp())[0].output_token_ids
    rest = obs.REGISTRY.get(
        "gllm_kvswap_prefix_restore_pages_total").get() - rest0
    assert rest > 0, "prompt replay never hit the host tier"
    # the digest-verified restore must reproduce the uninterrupted output
    # (garbage KV would change the continuation)
    assert got2 == want
