"""DeepSeek V3.2 sparse attention (DSA) — VERDICT r1 item 8.

The correctness oracle is the reference's own
(docs/deepseek_sparse_attention_design.md:36-40): for prompts no longer
than index_topk the top-k selects every key, so sparse output must equal
dense output byte-for-byte. Both engines share ONE param pytree (the dense
path simply never reads the indexer leaves).
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.sampling_params import SamplingParams

V32 = dict(
    architecture="DeepseekV32ForCausalLM", vocab_size=256, hidden_size=64,
    num_layers=3, num_heads=4, num_kv_heads=1, head_dim=24,
    intermediate_size=96, max_position=512,
    q_lora_rank=32, kv_lora_rank=32, qk_nope_head_dim=16,
    qk_rope_head_dim=8, v_head_dim=16,
    first_k_dense_replace=1, num_experts=4, num_experts_per_tok=2,
    moe_intermediate_size=32, n_shared_experts=1,
    routed_scaling_factor=1.0, scoring_func="sigmoid",
    topk_method="noaux_tc", n_group=2, topk_group=1, norm_topk_prob=True,
    index_n_heads=2, index_head_dim=16, index_topk=64,
)


def build_llm(mcfg, params=None, **cache_kw):
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=128,
        scheduler=SchedulerConfig(max_prefill_tokens=64),
        cache=CacheConfig(page_size=4, num_pages=128, **cache_kw))
    return LLM(config=cfg, model_cfg=mcfg, params=params)


def test_dsa_sparse_equals_dense_when_topk_covers():
    from gllm_tpu.models import deepseek
    mcfg_sparse = ModelConfig(**V32)
    params = deepseek.init_params(mcfg_sparse, seed=3, dtype=jnp.float32)
    # dense twin: same weights, DSA off (indexer leaves simply unread)
    mcfg_dense = dataclasses.replace(mcfg_sparse, index_topk=0,
                                     index_n_heads=0)

    rng = np.random.default_rng(0)
    prompts = [[int(x) for x in rng.integers(2, 250, size=int(n))]
               for n in rng.integers(3, 40, size=4)]
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    sparse = [o.output_token_ids
              for o in build_llm(mcfg_sparse, params).generate(
                  prompt_token_ids=prompts, sampling_params=sp)]
    dense = [o.output_token_ids
             for o in build_llm(mcfg_dense, params).generate(
                 prompt_token_ids=prompts, sampling_params=sp)]
    assert sparse == dense


def test_dsa_chunked_prefill_matches_unchunked():
    """Index-K cache carries across prefill chunks."""
    from gllm_tpu.models import deepseek
    mcfg = ModelConfig(**V32)
    params = deepseek.init_params(mcfg, seed=5, dtype=jnp.float32)
    rng = np.random.default_rng(2)
    prompt = [int(x) for x in rng.integers(2, 250, size=40)]
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    big = build_llm(mcfg, params).generate(
        prompt_token_ids=[prompt], sampling_params=sp)[0]

    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=128,
        scheduler=SchedulerConfig(max_prefill_tokens=8,
                                  min_prefill_tokens=4),
        cache=CacheConfig(page_size=4, num_pages=128))
    chunked = LLM(config=cfg, model_cfg=mcfg, params=params).generate(
        prompt_token_ids=[prompt], sampling_params=sp)[0]
    assert big.output_token_ids == chunked.output_token_ids


def test_dsa_truncated_topk_still_serves():
    """topk smaller than the context: the sparse path must run and finish
    (output differs from dense by design — only liveness + shape here)."""
    mcfg = dataclasses.replace(ModelConfig(**V32), index_topk=8)
    llm = build_llm(mcfg)
    rng = np.random.default_rng(1)
    prompt = [int(x) for x in rng.integers(2, 250, size=30)]
    out = llm.generate(
        prompt_token_ids=[prompt],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))[0]
    assert len(out.output_token_ids) == 6
    mm = llm.memory_manager
    assert mm.num_free_pages == mm.allocator.num_total


# ---- fp8 index-K cache (VERDICT r03 missing #3) ----------------------------

def _greedy(llm, prompts, n=8):
    return [o.output_token_ids for o in llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=n,
                                       ignore_eos=True))]


def test_fp8_index_cache_is_default_and_sized():
    """The index-K cache stores fp8 payloads + f32 per-token scales
    (reference store_index_k_fp8 132-byte layout) and the page-budget
    accounting reflects it."""
    from gllm_tpu.models import deepseek
    mcfg = ModelConfig(**V32)
    llm = build_llm(mcfg)
    kv = llm.runner.kv
    assert kv.index_k.dtype == jnp.float8_e4m3fn
    assert kv.index_scale is not None
    assert kv.index_scale.shape == kv.index_k.shape[:-1]
    # bytes/page: latent*itemsize + index_head_dim*1 + 4 (scale)
    per_tok = (mcfg.mla_cache_width * 4
               + mcfg.index_head_dim + 4)
    assert llm.runner._kv_bytes_per_page() == \
        mcfg.num_layers * 4 * per_tok


def test_fp8_index_cache_matches_native(monkeypatch):
    """Greedy outputs with the fp8 index cache equal the native-dtype
    cache: on these float32 tiny models the quantization error is far
    below the argmax decision margins, and the sparse==dense oracle
    (above) already ran with fp8 on."""
    from gllm_tpu.models import deepseek
    mcfg = ModelConfig(**V32)
    params = deepseek.init_params(mcfg, seed=3, dtype=jnp.float32)
    prompts = [[7, 3, 11, 23, 9, 2], [5, 5, 19]]
    fp8 = _greedy(build_llm(mcfg, params=params), prompts)
    monkeypatch.setenv("GLLM_TPU_DSA_INDEX_DTYPE", "native")
    native = _greedy(build_llm(mcfg, params=params), prompts)
    monkeypatch.delenv("GLLM_TPU_DSA_INDEX_DTYPE")
    assert fp8 == native


def test_fp8_scoring_flag(monkeypatch):
    """GLLM_DSA_FP8_SCORE=1 (reference flag name) scores the indexer with
    fp8 operands; the tiny-model greedy outputs still match the f32
    scoring path (selection indices survive the quantization)."""
    from gllm_tpu.models import deepseek
    mcfg = ModelConfig(**V32)
    params = deepseek.init_params(mcfg, seed=3, dtype=jnp.float32)
    prompts = [[7, 3, 11, 23, 9, 2, 31, 8]]
    base = _greedy(build_llm(mcfg, params=params), prompts)
    monkeypatch.setenv("GLLM_DSA_FP8_SCORE", "1")
    fp8s = _greedy(build_llm(mcfg, params=params), prompts)
    monkeypatch.delenv("GLLM_DSA_FP8_SCORE")
    assert base == fp8s
