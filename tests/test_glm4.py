"""GLM4: sandwich norms + partial interleaved rotary, HF oracle."""

import torch

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams


def test_glm4_greedy_equivalence(tmp_path):
    from transformers import Glm4Config, Glm4ForCausalLM
    torch.manual_seed(17)
    hf = Glm4ForCausalLM(Glm4Config(
        vocab_size=128, hidden_size=64, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        head_dim=16, partial_rotary_factor=0.5, attention_bias=True,
        max_position_embeddings=256, eos_token_id=0, pad_token_id=0,
        tie_word_embeddings=False))
    hf.eval()
    hf.save_pretrained(tmp_path, safe_serialization=True)

    cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                       max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=64))
    llm = LLM(config=cfg)
    prompts = [[7, 3, 56, 21], [99, 14, 2, 8, 30]]
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
    for p, out in zip(prompts, outs):
        ids = list(p)
        with torch.no_grad():
            for _ in range(8):
                ids.append(int(hf(torch.tensor([ids])).logits[0, -1]
                               .argmax()))
        assert out.output_token_ids == ids[len(p):], (p,
                                                      out.output_token_ids,
                                                      ids[len(p):])
