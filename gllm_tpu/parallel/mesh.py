"""Device mesh construction and sharding-constraint helpers.

Axis names (the TPU counterpart of the reference's pp×dp×tp rank grid,
dist_utils.py:149-263):

- ``dp``: data/attention-parallel replicas (reference DP attention)
- ``tp``: tensor parallel (Megatron column/row splits → mesh-axis shardings)
- ``ep`` is not a separate axis: experts shard over dp×tp flattened, exactly
  like the reference's EP = dp*tp (dist_utils.py:81-86).
- ``pp`` stages are separate jit programs per host group (not a GSPMD axis);
  see gllm_tpu/parallel/pipeline.py.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_SP = "sp"


def make_mesh(dp: int = 1, tp: int = 1, sp: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    # axis order (dp, sp, tp): tp innermost so its all-reduces ride
    # adjacent chips; the sp ring's neighbor exchanges stay within the
    # next-contiguous block
    if devices is None:
        devices = jax.devices()
    n = dp * tp * sp
    if len(devices) < n:
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, sp, tp)
    return Mesh(arr, (AXIS_DP, AXIS_SP, AXIS_TP))


def active_mesh():
    """The mesh bound by the innermost ``mesh_context`` (or None).

    Version shim: newer jax exposes ``jax.sharding.get_abstract_mesh``;
    0.4.x tracks the ``with mesh:`` context in thread_resources. Both
    returns carry ``.shape_tuple``."""
    get_am = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_am is not None:
        return get_am()
    from jax._src import mesh as _mesh_lib
    m = _mesh_lib.thread_resources.env.physical_mesh
    if m is None or m.empty:
        return None
    return m


def compat_shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                     axis_names=None):
    """``jax.shard_map`` across jax versions.

    Newer jax: pass through (``mesh=None`` + ``axis_names`` binds the
    context abstract mesh with only those axes manual). 0.4.x (this
    image): translate onto ``jax.experimental.shard_map`` — ``check_vma``
    → ``check_rep``, partial-manual via ``auto`` = the mesh axes NOT in
    ``axis_names``, and ``mesh=None`` resolves to the context mesh."""
    try:
        from jax import shard_map as sm
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return sm(f, **kw)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
        m = mesh if mesh is not None else active_mesh()
        auto = (frozenset(m.axis_names) - frozenset(axis_names)
                if axis_names is not None else frozenset())
        return sm(f, mesh=m, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma, auto=auto)


@contextlib.contextmanager
def mesh_context(mesh: Optional[Mesh]):
    if mesh is None:
        yield
        return
    set_mesh = getattr(jax.sharding, "set_mesh", None)
    if set_mesh is not None:
        with set_mesh(mesh):
            yield
    else:
        # jax 0.4.x: Mesh itself is the context manager binding the
        # active mesh that bare-PartitionSpec sharding constraints read
        with mesh:
            yield


def shard_hint(x, *spec):
    """with_sharding_constraint that degrades gracefully:

    - no active mesh (single-chip): no-op, same traced code everywhere
    - axis name absent from the mesh: that dim becomes unsharded
    - dim not divisible by the axis size: unsharded (matches the
      divisibility gating in parallel/shardings.py — e.g. 4 kv heads on
      tp=8 stay replicated instead of forcing reshard collectives)
    """
    mesh = active_mesh()
    if mesh is None or not mesh.shape_tuple:
        return x
    sizes = dict(mesh.shape_tuple)

    def axis_ok(name, dim):
        size = sizes.get(name)
        return size is not None and x.shape[dim] % size == 0

    cleaned = []
    for dim, s in enumerate(spec):
        if s is None:
            cleaned.append(None)
        elif isinstance(s, str):
            cleaned.append(s if axis_ok(s, dim) else None)
        else:  # tuple of axes
            cleaned.append(s if all(axis_ok(a, dim) for a in s) else None)
    return jax.lax.with_sharding_constraint(x, P(*cleaned))


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
