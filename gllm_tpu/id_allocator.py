"""FIFO id pool with O(1) operations.

Same contract as the reference's IDAllocator
(/root/reference/gllm/id_allocator.py:4-48): FIFO popleft for fresh ids, O(1)
targeted allocate (prefix-cache hits re-claim a specific page id), O(1) free.
Backed by an OrderedDict used as an ordered set.
"""

from __future__ import annotations

from collections import OrderedDict


class IDAllocator:
    def __init__(self, num_ids: int, start: int = 0):
        self._free: OrderedDict[int, None] = OrderedDict(
            (i, None) for i in range(start, start + num_ids))
        self.num_total = num_ids

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_total - len(self._free)

    def allocate(self) -> int:
        """Pop the oldest free id (FIFO)."""
        if not self._free:
            raise RuntimeError("IDAllocator exhausted")
        id_, _ = self._free.popitem(last=False)
        return id_

    def allocate_id(self, id_: int) -> None:
        """Claim a specific id (must currently be free)."""
        del self._free[id_]

    def is_free(self, id_: int) -> bool:
        return id_ in self._free

    def free(self, id_: int) -> None:
        if id_ in self._free:
            raise RuntimeError(f"double free of id {id_}")
        self._free[id_] = None
