"""Ragged paged attention — dispatch + XLA reference implementation.

This is the core attention path, covering what the reference gets from
sgl_kernel's ``flash_attn_with_kvcache`` / ``flash_attn_varlen_func``
(/root/reference/gllm/layers/attention.py:92-140): one varlen call serving a
mixed batch of prefill chunks and decode rows against the paged KV cache, with
causal masking relative to each sequence's already-computed context (chunked
prefill attends to all cached tokens plus the causal part of its own chunk).

Three implementations:
- ``xla``: gather-based reference. Runs on any backend (CPU tests, fallback),
  numerically the oracle for the Pallas kernels.
- ``pallas``: pure-decode batches (max_q_len == 1) run the per-sequence
  decode kernel (gllm_tpu/ops/pallas/decode_attention.py); mixed/prefill
  batches run the ragged varlen kernel
  (gllm_tpu/ops/pallas/ragged_attention.py). Both stream KV pages through
  VMEM with double-buffered DMA; MLA passes ``v_cache=None`` so values are
  read as the latent prefix of each key block (one DMA stream).
- ``unified``: the ``--unified-step`` path — EVERY paged step (decode,
  mixed, prefill; int8-KV dequant included) runs the ONE ragged kernel
  with per-row-class block geometry and AMLA mul-by-add rescaling
  (``ragged_paged_attention(unified=True)``,
  docs/overlap_scheduling.md#unified-step). The decode kernel is kept
  only as the legacy path / parity oracle.

Metadata layout (built by the runner, all padded to static bucket shapes):
- cu_q_lens: [S+1] int32 — cumulative query lengths (padded seqs repeat the
  last value → q_len 0)
- kv_lens:   [S] int32 — per-seq total context AFTER this step's tokens
- page_table:[S, max_pages] int32 — padded entries point at the dummy page
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class AttentionMetadata(NamedTuple):
    cu_q_lens: jnp.ndarray    # [S+1] int32
    kv_lens: jnp.ndarray      # [S] int32
    page_table: jnp.ndarray   # [S, max_pages] int32
    num_seqs: jnp.ndarray     # [] int32 (informational; padding is masked
                              # out via q_len == 0 rows)


NEG_INF = float("-inf")

# TP shard context: (mesh, axis_name), set by the runner when the Pallas
# path must run per-TP-shard under shard_map (q and KV are head-sharded, so
# the kernels partition cleanly — each shard streams only its own heads'
# pages). Read at trace time of the runner's step fn; one active
# pallas+tp runner per process (every ModelRunner.__init__ resets it).
_SHARD_CTX = None


def set_shard_context(mesh, axis_name: str = "tp") -> None:
    global _SHARD_CTX
    _SHARD_CTX = None if mesh is None else (mesh, axis_name)


def pallas_tp_compatible(num_q_heads: int, num_kv_heads: int,
                         tp: int) -> bool:
    """Can the Pallas kernels run per-TP-shard?

    Heads-sharded case (Hkv % tp == 0): per-shard GQA group is unchanged.
    KV-replicated case (small Hkv / MLA MQA — matches kv_cache_specs /
    latent_kv_specs): tp % Hkv == 0 means each shard's contiguous q-head
    slice belongs to exactly ONE kv head (heads are grouped kv-head-major),
    which the shard slices out and runs in MQA mode."""
    if num_q_heads % tp:
        return False
    return num_kv_heads % tp == 0 or tp % num_kv_heads == 0


def paged_attention(q, k_cache, v_cache, metadata, *, scale, max_q_len,
                    impl="xla", v_dim=None, k_scale=None, v_scale=None):
    """Public entry: dispatch to the (jitted) single-shard implementation,
    wrapping the Pallas path in shard_map when a TP shard context is set.
    ``k_scale``/``v_scale`` ([num_pages, Hkv] f32) mark an int8 quantized
    cache — both implementations dequantize on the read path (in-kernel
    for Pallas, on the gathered pages for XLA)."""
    if impl in ("pallas", "unified") and _SHARD_CTX is not None:
        mesh, axis = _SHARD_CTX
        tp = mesh.shape[axis]
        if tp > 1:
            return _pallas_sharded(q, k_cache, v_cache, metadata,
                                   scale=scale, max_q_len=max_q_len,
                                   v_dim=v_dim, mesh=mesh, axis=axis,
                                   k_scale=k_scale, v_scale=v_scale,
                                   impl=impl)
    return _paged_attention(q, k_cache, v_cache, metadata, k_scale,
                            v_scale, scale=scale, max_q_len=max_q_len,
                            impl=impl, v_dim=v_dim)


def _pallas_sharded(q, k_cache, v_cache, metadata, *, scale, max_q_len,
                    v_dim, mesh, axis, k_scale=None, v_scale=None,
                    impl="pallas"):
    """Run the Pallas kernels per TP shard: q sharded on its head axis, KV
    sharded on the kv-head axis when divisible (else replicated — small-Hkv
    and MLA-MQA caches are replicated by kv_cache_specs), metadata
    replicated. The per-shard call sees plain smaller arrays, so the
    kernels run untouched; GSPMD moves nothing (shardings already match
    the layer's activation/cache placement)."""
    from gllm_tpu.parallel.mesh import compat_shard_map as shard_map
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape[axis]
    num_q_heads = q.shape[1]
    num_kv_heads = k_cache.shape[2]
    if not pallas_tp_compatible(num_q_heads, num_kv_heads, tp):
        raise ValueError(
            f"pallas tp={tp} incompatible with Hq={num_q_heads} "
            f"Hkv={num_kv_heads}")
    kv_sharded = num_kv_heads % tp == 0
    if k_scale is not None and not kv_sharded:
        # the replicated-KV MQA-slice path below is gated off for int8
        # (runner._check_kv_quant rejects the topology up front)
        raise NotImplementedError(
            "int8 KV cache needs num_kv_heads % tp == 0 on the pallas "
            "path")
    qs = P(None, axis, None)
    ks = P(None, None, axis, None) if kv_sharded else P(None, None, None,
                                                        None)
    ss = P(None, axis)          # scales shard with the kv-head axis
    md_specs = AttentionMetadata(P(None), P(None), P(None, None), P())

    def inner(q, k, v, md, ksc=None, vsc=None):
        if not kv_sharded and num_kv_heads > 1:
            # KV replicated with tp % Hkv == 0: this shard's contiguous
            # q-head slice belongs to exactly one kv head (kv-head-major
            # grouping) — slice it out and run the kernels in MQA mode.
            head = jax.lax.axis_index(axis) // (tp // num_kv_heads)
            k = jax.lax.dynamic_slice_in_dim(k, head, 1, axis=2)
            if v is not None:
                v = jax.lax.dynamic_slice_in_dim(v, head, 1, axis=2)
        return _paged_attention(q, k, v, md, ksc, vsc, scale=scale,
                                max_q_len=max_q_len, impl=impl,
                                v_dim=v_dim)

    # Inside an already-set mesh context (the runner's step trace, or the
    # dp-manual shard_map region where the dp axis is Manual) the inner
    # shard_map must bind the CONTEXT abstract mesh with only the tp axis
    # going manual (mesh=None infers it). Standalone (unit tests, no
    # context) the concrete mesh is bound fully-manual — partial-manual
    # over a concrete multi-axis mesh trips spec normalization on
    # replicated in_specs.
    from gllm_tpu.parallel.mesh import active_mesh
    am = active_mesh()
    if am is not None and am.shape_tuple:
        kw = dict(mesh=None, axis_names={axis})
    else:
        kw = dict(mesh=mesh)
    if v_cache is None:
        fn = shard_map(lambda q, k, md: inner(q, k, None, md),
                       in_specs=(qs, ks, md_specs), out_specs=qs,
                       check_vma=False, **kw)
        return fn(q, k_cache, metadata)
    if k_scale is not None:
        fn = shard_map(inner, in_specs=(qs, ks, ks, md_specs, ss, ss),
                       out_specs=qs, check_vma=False, **kw)
        return fn(q, k_cache, v_cache, metadata, k_scale, v_scale)
    fn = shard_map(inner, in_specs=(qs, ks, ks, md_specs),
                   out_specs=qs, check_vma=False, **kw)
    return fn(q, k_cache, v_cache, metadata)


@functools.partial(jax.jit, static_argnames=("max_q_len", "scale", "impl",
                                             "v_dim"))
def _paged_attention(
    q: jnp.ndarray,            # [T, Hq, D]
    k_cache: jnp.ndarray,      # [num_pages, page_size, Hkv, D]
    v_cache,                   # [P, page, Hkv, Dv] or None → v = k[:, :Dv]
                               # (MLA absorbed: values are the latent
                               # prefix of the keys — one cache, one DMA
                               # stream)
    metadata: AttentionMetadata,
    k_scale=None,              # [num_pages, Hkv] f32: int8 cache scales
    v_scale=None,              # (per page per kv head; None = fp cache)
    *,
    scale: float,
    max_q_len: int,
    impl: str = "xla",
    v_dim: Optional[int] = None,
) -> jnp.ndarray:
    if v_cache is None and v_dim is None:
        raise ValueError("v_dim required when v_cache is None")
    # Packed lane layout (kv_pack > 1): the cache stores ``pack`` adjacent
    # kv heads per row — [P, ps, Hkv/pack, D*pack] — so head_dim < 128
    # models still meet Mosaic's 128-lane tiling. Detected structurally:
    # non-MLA caches otherwise always have last dim == head_dim.
    pack = (k_cache.shape[-1] // q.shape[-1]
            if v_cache is not None and k_cache.shape[-1] != q.shape[-1]
            else 1)
    if impl == "xla":
        if v_cache is None:
            v_cache = k_cache[..., :v_dim]
        elif pack > 1:
            P_, ps = k_cache.shape[:2]
            hkv = k_cache.shape[2] * pack
            k_cache = k_cache.reshape(P_, ps, hkv, q.shape[-1])
            v_cache = v_cache.reshape(P_, ps, hkv, q.shape[-1])
            if k_scale is not None:
                # packed row [h_p, D*pack] unpacks to heads h_p*pack+j —
                # repeat each packed-group scale over its pack members
                k_scale = jnp.repeat(k_scale, pack, axis=1)
                v_scale = jnp.repeat(v_scale, pack, axis=1)
        return _xla_paged_attention(q, k_cache, v_cache, metadata,
                                    scale=scale, max_q_len=max_q_len,
                                    k_scale=k_scale, v_scale=v_scale)
    if impl in ("pallas", "unified"):
        backend = jax.default_backend()
        if backend == "cpu":
            interpret = True
        elif backend in ("tpu", "axon"):
            interpret = False
        else:
            raise NotImplementedError(
                f"pallas attention unsupported on backend {backend!r}; "
                "use impl='xla'")
        slot = None
        if pack > 1:
            # Expand q into block-diagonal 128-lane rows: head h's values
            # occupy the lane block its kv head holds inside the packed
            # row; the other pack-1 blocks are zero, so the kernel's
            # q·k_packed dot contracts to exactly the head's own scores
            # (2× MAC waste — irrelevant in the bandwidth-bound regime).
            T, num_q_heads, D = q.shape
            group = num_q_heads // (k_cache.shape[2] * pack)
            slot = (jnp.arange(num_q_heads, dtype=jnp.int32)
                    // group) % pack
            onehot = jax.nn.one_hot(slot, pack, dtype=q.dtype)
            q = (q[:, :, None, :] * onehot[None, :, :, None]
                 ).reshape(T, num_q_heads, pack * D)

        if impl == "unified":
            # ONE kernel, one geometry family, for every paged step:
            # decode rows are q_len=1 rows of the ragged batch, handled
            # by the kernel's decode-class blocks (grouped round-robin
            # fetch — no masked-row waste, no per-seq DMA chain).
            from gllm_tpu.ops.pallas.ragged_attention import (
                ragged_paged_attention)
            from gllm_tpu.ops.pallas.tuning import get as tuned
            cfg = tuned("unified")
            out = ragged_paged_attention(
                q, k_cache, v_cache, metadata.cu_q_lens, metadata.kv_lens,
                metadata.page_table, scale=scale, interpret=interpret,
                v_dim=v_dim, q_block=cfg["q_block"],
                kv_block=cfg["kv_block"], unified=True,
                group_size=int(cfg.get("group", 4)),
                k_scale=k_scale, v_scale=v_scale)
        elif max_q_len == 1:
            # Pure-decode batch: T == S, one query row per sequence (the
            # layout prepare.py emits for max_q_len == 1). The per-seq
            # decode kernel wins here: its [Hkv, G, BK] dot shape avoids
            # the ragged kernel's masked-row waste for 1-token rows.
            if q.shape[0] != metadata.kv_lens.shape[0]:
                raise ValueError(
                    f"pallas decode path requires T == S, got T={q.shape[0]} "
                    f"S={metadata.kv_lens.shape[0]}")
            from gllm_tpu.ops.pallas.decode_attention import (
                paged_decode_attention)
            from gllm_tpu.ops.pallas.tuning import get as tuned
            cfg = tuned("decode")
            out = paged_decode_attention(
                q, k_cache, v_cache, metadata.kv_lens, metadata.page_table,
                scale=scale, interpret=interpret, v_dim=v_dim,
                kv_block=cfg["kv_block"],
                group_size=int(cfg.get("group", 1)),
                k_scale=k_scale, v_scale=v_scale)
        else:
            from gllm_tpu.ops.pallas.ragged_attention import (
                ragged_paged_attention)
            from gllm_tpu.ops.pallas.tuning import get as tuned
            blocks = tuned("ragged")
            out = ragged_paged_attention(
                q, k_cache, v_cache, metadata.cu_q_lens, metadata.kv_lens,
                metadata.page_table, scale=scale, interpret=interpret,
                v_dim=v_dim, q_block=blocks["q_block"],
                kv_block=blocks["kv_block"],
                k_scale=k_scale, v_scale=v_scale)
        if pack > 1:
            # The packed p·v_packed dot produced every lane block; keep
            # each head's own block (the rest mixed other heads' values).
            T, num_q_heads = out.shape[:2]
            D = out.shape[-1] // pack
            out = out.reshape(T, num_q_heads, pack, D)
            out = jnp.take_along_axis(
                out, slot[None, :, None, None], axis=2)[:, :, 0]
        return out
    raise ValueError(f"unknown attention impl {impl!r}")


def _xla_paged_attention(q, k_cache, v_cache, md: AttentionMetadata, *,
                         scale: float, max_q_len: int,
                         k_scale=None, v_scale=None):
    T, num_q_heads, head_dim = q.shape
    num_pages, page_size, num_kv_heads, _ = k_cache.shape
    v_dim = v_cache.shape[-1]     # may differ from head_dim (MLA: values
                                  # are the latent prefix of the keys)
    S, max_pages = md.page_table.shape
    group = num_q_heads // num_kv_heads
    max_kv = max_pages * page_size

    q_lens = md.cu_q_lens[1:] - md.cu_q_lens[:-1]                    # [S]
    # Gather per-seq query rows → [S, Qmax, Hq, D]
    local_q = jnp.arange(max_q_len, dtype=jnp.int32)                 # [Qmax]
    q_idx = jnp.clip(md.cu_q_lens[:-1, None] + local_q[None, :], 0, T - 1)
    q_valid = local_q[None, :] < q_lens[:, None]                     # [S, Qmax]
    qg = q[q_idx]                                                    # [S,Qmax,Hq,D]

    # Gather per-seq KV pages → [S, max_kv, Hkv, D]. int8 caches
    # dequantize on the GATHERED pages (page-granular scales gathered by
    # the same table) — the full-precision cache never materializes.
    kg = k_cache[md.page_table]         # [S, MP, ps, Hkv, D]
    vg = v_cache[md.page_table]
    if k_scale is not None:
        kg = kg.astype(jnp.float32) * \
            k_scale[md.page_table][:, :, None, :, None]
        vg = vg.astype(jnp.float32) * \
            v_scale[md.page_table][:, :, None, :, None]
    kg = kg.reshape(S, max_kv, num_kv_heads, head_dim)
    vg = vg.reshape(S, max_kv, num_kv_heads, v_dim)

    # Causal+context mask: query at local index t has absolute position
    # kv_len - q_len + t; key j is visible iff j <= that position.
    kv_pos = jnp.arange(max_kv, dtype=jnp.int32)                     # [K]
    q_pos = (md.kv_lens[:, None] - q_lens[:, None] + local_q[None, :])
    visible = (kv_pos[None, None, :] <= q_pos[:, :, None])           # [S,Q,K]
    visible &= (kv_pos[None, None, :] < md.kv_lens[:, None, None])
    visible &= q_valid[:, :, None]

    qg = qg.reshape(S, max_q_len, num_kv_heads, group, head_dim)
    scores = jnp.einsum("sqhgd,skhd->shgqk", qg.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = jnp.where(visible[:, None, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # Rows with no visible keys (padding) produce NaN-free zeros:
    probs = jnp.where(visible[:, None, None, :, :], probs, 0.0)
    out = jnp.einsum("shgqk,skhd->sqhgd", probs, vg.astype(jnp.float32))
    out = out.reshape(S, max_q_len, num_q_heads, v_dim).astype(q.dtype)

    # Scatter back to the ragged token layout. Padded/invalid rows carry
    # zeros and clipped duplicate indices — scatter-add keeps it exact.
    out = jnp.where(q_valid[:, :, None, None], out, 0)
    flat = jnp.zeros((T, num_q_heads, v_dim), q.dtype)
    return flat.at[q_idx.reshape(-1)].add(
        out.reshape(S * max_q_len, num_q_heads, v_dim))
