"""Architecture registry.

Maps HF ``architectures[0]`` strings to model definitions, like the
reference's architecture→class table (/root/reference/gllm/model_loader.py:
499-536). A ModelDef bundles the functional pieces the runner needs.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

from gllm_tpu.models.config import ModelConfig


@dataclasses.dataclass(frozen=True)
class ModelDef:
    family: str
    init_params: Callable
    forward: Callable
    compute_logits: Callable
    make_rope_table: Callable
    load_params: Callable          # (model_dir, cfg, dtype) -> params
    init_kv_cache: Callable
    param_specs: Callable          # (cfg, tp) -> PartitionSpec pytree
    kv_specs: Callable             # (cfg, tp) -> cache PartitionSpec pytree
    # VL models: (params, cfg, pixels, grid_thw) -> [n_rows, mm_embed_dim]
    embed_mm: Optional[Callable] = None


def _dense_def() -> ModelDef:
    from gllm_tpu.models import dense, loader
    from gllm_tpu.parallel.shardings import (dense_param_specs,
                                             kv_cache_specs)
    return ModelDef(
        family="dense",
        init_params=dense.init_params,
        forward=dense.forward,
        compute_logits=dense.compute_logits,
        make_rope_table=dense.make_rope_table,
        load_params=loader.load_dense_params,
        init_kv_cache=dense.init_kv_cache,
        param_specs=dense_param_specs,
        kv_specs=kv_cache_specs,
    )


_DENSE_ARCHS = (
    "ChatGLMForConditionalGeneration",
    "ChatGLMModel",
    "Glm4ForCausalLM",
    "GlmForCausalLM",
    "LlamaForCausalLM",
    "MistralForCausalLM",
    "Qwen2ForCausalLM",
    "Qwen3ForCausalLM",
)


def _vl_def() -> ModelDef:
    from gllm_tpu.models import qwen2_5_vl
    from gllm_tpu.parallel.shardings import kv_cache_specs, vl_param_specs
    return ModelDef(
        family="vl",
        init_params=qwen2_5_vl.init_params,
        forward=qwen2_5_vl.forward,
        compute_logits=qwen2_5_vl.compute_logits,
        make_rope_table=qwen2_5_vl.make_rope_table,
        load_params=qwen2_5_vl.load_params,
        init_kv_cache=qwen2_5_vl.init_kv_cache,
        param_specs=vl_param_specs,
        kv_specs=kv_cache_specs,
        embed_mm=qwen2_5_vl.embed_mm,
    )


def _vl3_def() -> ModelDef:
    from gllm_tpu.models import qwen3_vl
    from gllm_tpu.parallel.shardings import kv_cache_specs, vl3_param_specs
    return ModelDef(
        family="vl3",
        init_params=qwen3_vl.init_params,
        forward=qwen3_vl.forward,
        compute_logits=qwen3_vl.compute_logits,
        make_rope_table=qwen3_vl.make_rope_table,
        load_params=qwen3_vl.load_params,
        init_kv_cache=qwen3_vl.init_kv_cache,
        param_specs=vl3_param_specs,
        kv_specs=kv_cache_specs,
        embed_mm=qwen3_vl.embed_mm,
    )


def get_model_def(cfg: ModelConfig) -> ModelDef:
    if cfg.architecture in _DENSE_ARCHS:
        return _dense_def()
    if cfg.architecture in _MOE_ARCHS:
        from gllm_tpu.models.registry_moe import moe_def
        return moe_def()
    if cfg.architecture in _MLA_ARCHS:
        from gllm_tpu.models.registry_moe import deepseek_def
        return deepseek_def()
    if cfg.architecture in _VL_ARCHS:
        return _vl_def()
    if cfg.architecture in _VL3_ARCHS:
        return _vl3_def()
    if cfg.architecture == "KimiK25ForConditionalGeneration":
        from gllm_tpu.models import kimi
        from gllm_tpu.parallel.shardings import (kimi_param_specs,
                                                 latent_kv_specs)
        return ModelDef(
            family="kimi",
            init_params=kimi.init_params,
            forward=kimi.forward,
            compute_logits=kimi.compute_logits,
            make_rope_table=kimi.make_rope_table,
            load_params=kimi.load_params,
            init_kv_cache=kimi.init_kv_cache,
            param_specs=kimi_param_specs,
            kv_specs=latent_kv_specs,
            embed_mm=kimi.embed_mm,
        )
    if cfg.architecture in _HYBRID_ARCHS:
        from gllm_tpu.models import hybrid
        from gllm_tpu.parallel.shardings import (hybrid_kv_specs,
                                                 hybrid_param_specs)
        return ModelDef(
            family="hybrid",
            init_params=hybrid.init_params,
            forward=hybrid.forward,
            compute_logits=hybrid.compute_logits,
            make_rope_table=hybrid.make_rope_table,
            load_params=hybrid.load_params,
            init_kv_cache=hybrid.init_kv_cache,
            param_specs=hybrid_param_specs,
            kv_specs=hybrid_kv_specs,
        )
    raise NotImplementedError(
        f"architecture {cfg.architecture!r} not supported yet; "
        f"dense: {_DENSE_ARCHS}, moe: {_MOE_ARCHS}, mla: {_MLA_ARCHS}, "
        f"vl: {_VL_ARCHS}")


_MOE_ARCHS = (
    "MixtralForCausalLM",
    "Qwen2MoeForCausalLM",
    "Qwen3MoeForCausalLM",
)

_MLA_ARCHS = (
    "DeepseekV2ForCausalLM",
    "DeepseekV3ForCausalLM",
    "DeepseekV32ForCausalLM",
)

_VL_ARCHS = (
    "Qwen2_5_VLForConditionalGeneration",
)

_VL3_ARCHS = (
    "Qwen3VLForConditionalGeneration",
    "Qwen3VLMoeForConditionalGeneration",
)

_HYBRID_ARCHS = (
    "Qwen3NextForCausalLM",
    "Qwen3_5ForCausalLM",
    "Qwen3_5MoeForCausalLM",
    # Real Qwen3.5 checkpoints ship the ConditionalGeneration arch string
    # (reference model_loader.py:527-531); same hybrid GDN stack.
    "Qwen3_5ForConditionalGeneration",
    "Qwen3_5MoeForConditionalGeneration",
)


def supported_architectures() -> Dict[str, str]:
    out = {a: "dense" for a in _DENSE_ARCHS}
    out.update({a: "moe" for a in _MOE_ARCHS})
    out.update({a: "mla-moe" for a in _MLA_ARCHS})
    out.update({a: "vl" for a in _VL_ARCHS})
    out.update({a: "vl3" for a in _VL3_ARCHS})
    out["KimiK25ForConditionalGeneration"] = "kimi"
    out.update({a: "hybrid" for a in _HYBRID_ARCHS})
    return out
