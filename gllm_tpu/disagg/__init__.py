"""Encoder disaggregation: vision towers in separate processes.

TPU-native re-design of the reference subsystem
(/root/reference/gllm/disagg/, ~2600 LoC): an LM server runs the language
model only (``skip_visual``); one or more encoder servers own pixel IO +
the ViT; a discovery registry with TTL leases lets either side start
first. The reference moves embeddings GPU→GPU over NIXL/UCX RDMA; on TPU
the natural landing zone is host RAM — our batch builder splices visual
rows host-side and ships them with the per-step H2D transfer — so the
data plane is a TCP slot-pool write (gllm_tpu/disagg/transfer.py), with
the same register/write/notify contract NIXL provides.
"""

from gllm_tpu.disagg.config import DisaggConfig

__all__ = ["DisaggConfig"]
