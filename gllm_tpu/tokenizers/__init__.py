"""Model-bundled tokenizer/encoder adapters (reference gllm/tokenizers/)."""
