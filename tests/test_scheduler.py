"""Scheduler unit tests: pure-Python, no device needed."""

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.memory_manager import make_memory_manager
from gllm_tpu.sampling_params import SamplingParams
from gllm_tpu.scheduler import Scheduler
from gllm_tpu.sequence import Sequence, SequenceStatus

EOS = 2


def make_engine(num_pages=64, page_size=4, maxp=16, maxd=8,
                method="chunked_prefill", prefix=False, max_num_seqs=32):
    cfg = EngineConfig(
        max_model_len=num_pages * page_size,
        max_num_seqs=max_num_seqs,
        scheduler=SchedulerConfig(schedule_method=method,
                                  max_prefill_tokens=maxp,
                                  min_prefill_tokens=4,
                                  max_decode_seqs=maxd),
        cache=CacheConfig(page_size=page_size, num_pages=num_pages,
                          enable_prefix_caching=prefix),
    )
    mm = make_memory_manager(num_pages, page_size, prefix)
    return cfg, mm, Scheduler(cfg, mm)


def run_steps(sched, n_steps, sample_token=7, eos=EOS):
    """Drive the scheduler with a fake model that always samples
    ``sample_token``. Returns all SeqOutputs."""
    outs = []
    for _ in range(n_steps):
        batch = sched.schedule_once()
        if batch is None:
            break
        tokens = [sample_token] * batch.num_seqs
        outs.extend(sched.process_output(batch, tokens, eos))
    return outs


def test_prefill_then_decode_until_length():
    _, _, sched = make_engine()
    seq = Sequence(0, list(range(10)), SamplingParams(max_tokens=3))
    sched.add_seq(seq)

    batch = sched.schedule_once()
    assert batch.num_seqs == 1
    assert batch.items[0].num_new_tokens == 10
    assert batch.items[0].samples
    sched.process_output(batch, [7], EOS)
    assert seq.num_computed_tokens == 10
    assert seq.token_ids[-1] == 7

    # two more decode steps hit max_tokens=3
    run_steps(sched, 10)
    assert seq.status is SequenceStatus.FINISHED
    assert seq.finish_reason == "length"
    assert seq.output_token_ids == [7, 7, 7]
    assert not sched.has_unfinished
    assert sched.mm.num_free_pages == sched.mm.allocator.num_total


def test_eos_finishes():
    _, _, sched = make_engine()
    seq = Sequence(0, [1, 3, 4], SamplingParams(max_tokens=50))
    sched.add_seq(seq)
    run_steps(sched, 5, sample_token=EOS)
    assert seq.finish_reason == "stop"
    assert seq.output_token_ids == [EOS]


def test_chunked_prefill_spans_iterations():
    _, _, sched = make_engine(maxp=8)
    seq = Sequence(0, list(range(20)), SamplingParams(max_tokens=2))
    sched.add_seq(seq)

    b1 = sched.schedule_once()
    assert b1.items[0].num_new_tokens == 8
    assert not b1.items[0].samples
    sched.process_output(b1, [0], EOS)
    assert seq.num_computed_tokens == 8
    assert seq.num_tokens == 20  # no token appended mid-prefill

    b2 = sched.schedule_once()
    assert b2.items[0].num_new_tokens == 8
    sched.process_output(b2, [0], EOS)

    b3 = sched.schedule_once()
    assert b3.items[0].num_new_tokens == 4
    assert b3.items[0].samples
    sched.process_output(b3, [9], EOS)
    assert seq.token_ids[-1] == 9


def test_decode_and_prefill_mixed_batch():
    _, _, sched = make_engine(maxp=16)
    a = Sequence(0, list(range(4)), SamplingParams(max_tokens=10))
    sched.add_seq(a)
    run_steps(sched, 1)  # a prefilled, now decoding
    b = Sequence(1, list(range(6)), SamplingParams(max_tokens=10))
    sched.add_seq(b)
    batch = sched.schedule_once()
    kinds = {it.seq.seq_id: it.num_new_tokens for it in batch.items}
    assert kinds == {0: 1, 1: 6}


def test_preemption_under_pressure_and_recovery():
    # 8 usable pages of 4 tokens = 32 KV slots. Both seqs pass adaptive
    # admission (new_token_ratio under-reserves), then their decode growth
    # (2 × 20 tokens = 10 pages) collides → preemption must kick in and both
    # must still run to completion.
    _, mm, sched = make_engine(num_pages=9, page_size=4, maxp=32)
    a = Sequence(0, list(range(4)), SamplingParams(max_tokens=16))
    b = Sequence(1, list(range(4)), SamplingParams(max_tokens=16))
    sched.add_seq(a)
    sched.add_seq(b)
    outs = run_steps(sched, 60)
    # Both must finish despite preemptions; all pages returned.
    assert a.status is SequenceStatus.FINISHED
    assert b.status is SequenceStatus.FINISHED
    assert sched.num_preemptions > 0
    assert mm.num_free_pages == mm.allocator.num_total
    assert len(a.output_token_ids) == 16
    assert len(b.output_token_ids) == 16


def test_abort_waiting_and_running():
    _, mm, sched = make_engine()
    a = Sequence(0, list(range(4)), SamplingParams(max_tokens=50))
    b = Sequence(1, list(range(4)), SamplingParams(max_tokens=50))
    sched.add_seq(a)
    sched.add_seq(b)
    run_steps(sched, 2)
    sched.abort_seq(0)  # running
    sched.abort_seq(1)  # running
    sched.schedule_once()
    assert a.status is SequenceStatus.ABORTED
    assert b.status is SequenceStatus.ABORTED
    assert mm.num_free_pages == mm.allocator.num_total
    assert not sched.has_unfinished


def test_decode_cap_rotates_fairly():
    _, _, sched = make_engine(maxd=2, maxp=64)
    seqs = [Sequence(i, list(range(4)), SamplingParams(max_tokens=50))
            for i in range(4)]
    for s in seqs:
        sched.add_seq(s)
    run_steps(sched, 1)  # all prefill in one batch
    for _ in range(8):
        batch = sched.schedule_once()
        assert batch.num_seqs <= 2
        sched.process_output(batch, [7] * batch.num_seqs, EOS)
    # every seq decoded roughly equally
    counts = [s.num_output_tokens for s in seqs]
    assert max(counts) - min(counts) <= 1


def test_split_pd_batches_are_pure():
    _, _, sched = make_engine(method="split_pd")
    a = Sequence(0, list(range(4)), SamplingParams(max_tokens=10))
    sched.add_seq(a)
    b1 = sched.schedule_once()  # pure prefill
    assert all(it.seq.is_prefilling for it in b1.items)
    sched.process_output(b1, [7], EOS)
    b = Sequence(1, list(range(4)), SamplingParams(max_tokens=10))
    sched.add_seq(b)
    b2 = sched.schedule_once()  # prefill work exists → prefill-only batch
    assert [it.seq.seq_id for it in b2.items] == [1]
    sched.process_output(b2, [7], EOS)
    b3 = sched.schedule_once()  # now pure decode
    assert sorted(it.seq.seq_id for it in b3.items) == [0, 1]
    assert all(it.num_new_tokens == 1 for it in b3.items)


def test_token_throttling_budget_shrinks_as_cache_fills():
    cfg, mm, sched = make_engine(num_pages=17, page_size=4, maxp=32,
                                 method="token_throttling")
    # empty cache → full budget
    full = sched._prefill_token_budget()
    a = Sequence(0, list(range(48)), SamplingParams(max_tokens=4))
    sched.add_seq(a)
    run_steps(sched, 1)
    pressured = sched._prefill_token_budget()
    assert pressured <= full


def test_prefix_cache_via_scheduler():
    _, mm, sched = make_engine(prefix=True, maxp=64)
    a = Sequence(0, list(range(16)), SamplingParams(max_tokens=2))
    sched.add_seq(a)
    run_steps(sched, 10)
    assert a.status is SequenceStatus.FINISHED
    b = Sequence(1, list(range(16)), SamplingParams(max_tokens=2))
    sched.add_seq(b)
    batch = sched.schedule_once()
    # 3 full pages (12 tokens) of the prompt hit the cache.
    assert batch.items[0].num_new_tokens == 4
    assert batch.items[0].computed_before == 12
    assert b.num_cached_tokens == 12


def test_enforce_eager_disables_async_tricks():
    from gllm_tpu.config import EngineConfig
    cfg = EngineConfig(enforce_eager=True, overlap_scheduling=True,
                       multi_step_decode=8)
    cfg.validate()
    assert cfg.overlap_scheduling is False
    assert cfg.multi_step_decode == 1
