"""Prompt-lookup (n-gram) speculative decoding — beyond the reference.

Correctness contract: greedy outputs are BYTE-IDENTICAL with and without
spec decoding (the verify step emits exactly the per-position argmax),
while accepted drafts reduce the number of engine steps. Covers: the
proposer, byte-identity on draft-friendly (repetitive) and draft-hostile
(random) workloads, EOS inside an accepted run, max-token/length caps,
non-greedy requests falling back in the same batch, and prefix-cache
interaction.
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams
from gllm_tpu.scheduler import propose_ngram_drafts


def test_proposer_basic():
    #           0  1  2  3  4  5  6  7
    toks = [5, 6, 7, 8, 5, 6]           # pattern (5,6) recurs
    assert propose_ngram_drafts(toks, 2, 3) == (7, 8, 5)
    assert propose_ngram_drafts(toks, 2, 1) == (7,)
    # no earlier occurrence → no drafts
    assert propose_ngram_drafts([1, 2, 3, 4], 2, 3) == ()
    # short sequence
    assert propose_ngram_drafts([1], 2, 3) == ()
    # most RECENT match wins
    toks2 = [5, 6, 9, 5, 6, 1, 5, 6]
    assert propose_ngram_drafts(toks2, 2, 2) == (1, 5)


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(7)
    d = str(tmp_path_factory.mktemp("tiny_spec"))
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=512, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    return d


def make_llm(ckpt, spec=False, prefix=False, **kw):
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=256,
        spec_decode="ngram" if spec else None, spec_k=4, spec_ngram=2,
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=prefix), **kw)
    return LLM(config=cfg)


# Greedy models on random weights loop quickly → the draft-friendly
# regime; a random prompt exercises cold proposals too.
PROMPTS = [
    [5, 9, 23, 5, 9, 23, 5, 9],          # immediate n-gram structure
    [7, 7, 7, 7],                        # degenerate repetition
    list(range(1, 30)),                  # no repeats in the prompt
    [101, 3, 101, 3, 101],
]


def greedy(llm, prompts, n=32, **sp_kw):
    sp = SamplingParams(temperature=0.0, max_tokens=n, ignore_eos=True,
                        **sp_kw)
    outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                        sampling_params=sp)
    return [o.output_token_ids for o in outs]


def test_spec_byte_identity_and_fewer_steps(ckpt):
    base = make_llm(ckpt)
    want = greedy(base, PROMPTS)
    base_steps = base.runner._step_count
    del base

    llm = make_llm(ckpt, spec=True)
    got = greedy(llm, PROMPTS)
    assert got == want, (got, want)
    st = llm.scheduler.spec_stats
    assert st["proposed"] > 0
    assert st["accepted"] > 0, "greedy loops must accept some drafts"
    assert llm.runner._step_count < base_steps, \
        (llm.runner._step_count, base_steps)


def test_spec_respects_eos_and_max_tokens(ckpt):
    """EOS inside an accepted draft run must truncate exactly like plain
    decoding (no ignore_eos), and max_tokens caps mid-run."""
    llm = make_llm(ckpt, spec=True)
    base = make_llm(ckpt)
    sp = dict(temperature=0.0, max_tokens=19)
    a = llm.generate(prompt_token_ids=[list(p) for p in PROMPTS],
                     sampling_params=SamplingParams(**sp))
    b = base.generate(prompt_token_ids=[list(p) for p in PROMPTS],
                      sampling_params=SamplingParams(**sp))
    for x, y in zip(a, b):
        assert x.output_token_ids == y.output_token_ids
        assert x.finish_reason == y.finish_reason


def test_spec_mixed_batch_with_sampling_requests(ckpt):
    """Greedy and penalized requests keep byte-identity with the non-spec
    engine (penalized requests speculate too: the verify rows see
    draft-prefix-adjusted logits via spec_adjust_logits); a seeded
    sampled request in the same batch speculates by rejection sampling,
    so it asserts run-to-run determinism instead of realization-identity
    with the non-spec engine."""
    llm = make_llm(ckpt, spec=True)
    llm2 = make_llm(ckpt, spec=True)
    base = make_llm(ckpt)
    prompts = [PROMPTS[0], PROMPTS[1], PROMPTS[2]]
    sps = [SamplingParams(temperature=0.0, max_tokens=16, ignore_eos=True),
           SamplingParams(temperature=0.8, seed=3, max_tokens=16,
                          ignore_eos=True),
           SamplingParams(temperature=0.0, repetition_penalty=1.3,
                          max_tokens=16, ignore_eos=True)]
    a = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                     sampling_params=sps)
    a2 = llm2.generate(prompt_token_ids=[list(p) for p in prompts],
                       sampling_params=sps)
    b = base.generate(prompt_token_ids=[list(p) for p in prompts],
                      sampling_params=sps)
    # greedy + penalized: byte-identical to non-spec
    assert a[0].output_token_ids == b[0].output_token_ids
    assert a[2].output_token_ids == b[2].output_token_ids
    # seeded sampled: deterministic under spec
    assert a[1].output_token_ids == a2[1].output_token_ids
    assert llm.scheduler.spec_stats["proposed"] > 0


def test_spec_with_prefix_cache_cold_warm(ckpt):
    """Prefix caching registers pages over multi-token commits; a warm
    re-run stays byte-identical."""
    llm = make_llm(ckpt, spec=True, prefix=True)
    want = greedy(make_llm(ckpt), [PROMPTS[0]], n=48)
    cold = greedy(llm, [PROMPTS[0]], n=48)
    warm = greedy(llm, [PROMPTS[0]], n=48)
    assert cold == want and warm == want


def test_spec_near_max_model_len(ckpt):
    """Drafts are trimmed so no row lands past max_model_len, and the
    length finish fires at the same token as the plain engine."""
    long_prompt = ([11, 13] * 120)[:238]          # close to 256 cap
    llm = make_llm(ckpt, spec=True)
    base = make_llm(ckpt)
    sp = SamplingParams(temperature=0.0, max_tokens=64, ignore_eos=True)
    a = llm.generate(prompt_token_ids=[list(long_prompt)],
                     sampling_params=sp)[0]
    b = base.generate(prompt_token_ids=[list(long_prompt)],
                      sampling_params=sp)[0]
    assert a.output_token_ids == b.output_token_ids
    assert a.finish_reason == b.finish_reason == "length"


def test_spec_stop_strings_capped_drafts_and_identical(ckpt):
    """Stop-string requests speculate with a capped draft length (k<=2,
    scheduler._propose_drafts); a never-matching stop keeps outputs
    identical to the plain engine."""
    llm = make_llm(ckpt, spec=True)
    base = make_llm(ckpt)
    sp = dict(temperature=0.0, max_tokens=24, ignore_eos=True,
              stop=["xyzzy"])     # never matches; exercises the path
    a = llm.generate(prompt_token_ids=[list(PROMPTS[0])],
                     sampling_params=SamplingParams(**sp))[0]
    b = base.generate(prompt_token_ids=[list(PROMPTS[0])],
                      sampling_params=SamplingParams(**sp))[0]
    assert a.output_token_ids == b.output_token_ids
    assert llm.scheduler.spec_stats["proposed"] > 0


class _CharTok:
    """1 char per token — makes text<->token mapping exact for stop
    tests."""
    eos_token_id = 0

    def decode(self, ids, skip_special_tokens=False):
        return "".join(chr(65 + (i % 26)) for i in ids)

    def encode(self, text):
        return [ord(c) - 65 for c in text]


def test_spec_stop_string_match_trims_exactly(ckpt):
    """A stop string completing INSIDE an accepted draft run: text is
    truncated before the match, over-committed tokens are trimmed, and
    output ids/usage equal the non-spec engine's (per-token stop scan)
    result byte-for-byte."""
    from gllm_tpu.config import CacheConfig, EngineConfig
    mk = lambda spec: LLM(config=EngineConfig(   # noqa: E731
        model=ckpt, dtype="float32", max_model_len=256,
        spec_decode="ngram" if spec else None, spec_k=4, spec_ngram=2,
        cache=CacheConfig(page_size=4, num_pages=128)),
        tokenizer=_CharTok())
    base = mk(False)
    free0 = base.scheduler.mm.num_free_pages
    probe = base.generate(prompt_token_ids=[list(PROMPTS[0])],
                          sampling_params=SamplingParams(
                              temperature=0.0, max_tokens=24,
                              ignore_eos=True))[0]
    # pick a stop string that completes mid-output (chars 6..7 of the
    # output text), so with spec_k=4 a draft run can overshoot it
    stop = probe.text[6:8]
    sp = SamplingParams(temperature=0.0, max_tokens=24, ignore_eos=True,
                        stop=[stop])
    b = base.generate(prompt_token_ids=[list(PROMPTS[0])],
                      sampling_params=sp)[0]
    llm = mk(True)
    a = llm.generate(prompt_token_ids=[list(PROMPTS[0])],
                     sampling_params=sp)[0]
    assert b.finish_reason == "stop" and a.finish_reason == "stop"
    assert a.text == b.text
    assert a.output_token_ids == b.output_token_ids
    assert a.num_output_tokens == b.num_output_tokens
    assert stop not in a.text
    # trimmed seqs must leak no pages
    assert llm.scheduler.mm.num_free_pages == \
        base.scheduler.mm.num_free_pages == free0


def test_spec_penalties_and_bias_byte_identity(ckpt):
    """Penalties + logit_bias requests speculate and stay byte-identical:
    the verify rows apply the same on-device adjustments (with
    draft-prefix counts) the plain sampler applies."""
    llm = make_llm(ckpt, spec=True)
    base = make_llm(ckpt)
    sp = SamplingParams(temperature=0.0, max_tokens=24, ignore_eos=True,
                        repetition_penalty=1.3, presence_penalty=0.4,
                        frequency_penalty=0.2,
                        logit_bias={7: 3.5, 23: -2.0})
    prompts = [PROMPTS[0], PROMPTS[1]]
    a = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                     sampling_params=[sp, sp])
    b = base.generate(prompt_token_ids=[list(p) for p in prompts],
                      sampling_params=[sp, sp])
    assert [o.output_token_ids for o in a] == \
        [o.output_token_ids for o in b]
    st = llm.scheduler.spec_stats
    assert st["proposed"] > 0 and st["accepted"] > 0


def test_spec_logprobs_match_plain(ckpt):
    """logprobs requests speculate; reported logprobs come from the
    verify rows' distributions and match the plain engine's exactly
    (greedy => same tokens, same log-softmax rows)."""
    llm = make_llm(ckpt, spec=True)
    base = make_llm(ckpt)
    sp = SamplingParams(temperature=0.0, max_tokens=16, ignore_eos=True,
                        logprobs=2)
    a = llm.generate(prompt_token_ids=[list(PROMPTS[0])],
                     sampling_params=sp)[0]
    b = base.generate(prompt_token_ids=[list(PROMPTS[0])],
                      sampling_params=sp)[0]
    assert a.output_token_ids == b.output_token_ids
    assert llm.scheduler.spec_stats["accepted"] > 0
    assert a.logprobs is not None and len(a.logprobs) == len(b.logprobs)
    for (ca, ia, va), (cb, ib, vb) in zip(a.logprobs, b.logprobs):
        assert ia == ib
        np.testing.assert_allclose(ca, cb, rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(va, vb, rtol=2e-4, atol=2e-5)


def test_spec_under_pp2(ckpt):
    """Speculative decoding through a pp=2 pipeline (last stage verifies)
    — byte-identical to the plain single-stage engine."""
    from gllm_tpu.config import ParallelConfig
    base = make_llm(ckpt)
    want = greedy(base, PROMPTS)
    del base
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=256,
        spec_decode="ngram", spec_k=4, spec_ngram=2,
        cache=CacheConfig(page_size=4, num_pages=128),
        parallel=ParallelConfig(pp=2))
    llm = LLM(config=cfg)
    got = greedy(llm, PROMPTS)
    assert got == want, (got, want)
    assert llm.scheduler.spec_stats["accepted"] > 0


@pytest.mark.parametrize("par", [dict(dp=2), dict(dp=2, pp=2),
                                 dict(tp=2)],
                         ids=["dp2", "dp2pp2", "tp2"])
def test_spec_under_dp(ckpt, par):
    """Speculative decoding under DP replicas (per-replica verify in the
    stacked program; independent pipelines under dp×pp) and TP (GSPMD
    shards the verify projection) — byte-identical to the plain
    single-replica engine."""
    from gllm_tpu.config import ParallelConfig
    base = make_llm(ckpt)
    want = greedy(base, PROMPTS)
    del base
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=256,
        spec_decode="ngram", spec_k=4, spec_ngram=2,
        cache=CacheConfig(page_size=4, num_pages=128),
        parallel=ParallelConfig(**par))
    llm = LLM(config=cfg)
    got = greedy(llm, PROMPTS)
    assert got == want, (got, want)
    assert sum(s.spec_stats["accepted"] for s in llm.schedulers) > 0


def test_spec_under_memory_pressure_preemption(ckpt):
    """A tiny KV pool forces preemption churn; speculation must drop
    drafts rather than cost a seq its KV, and greedy outputs stay
    identical to the plain engine under the SAME tiny pool (preemption
    may reorder work but never changes greedy content)."""
    def run(spec):
        cfg = EngineConfig(
            model=ckpt, dtype="float32", max_model_len=256,
            spec_decode="ngram" if spec else None, spec_k=4, spec_ngram=2,
            cache=CacheConfig(page_size=4, num_pages=28))
        llm = LLM(config=cfg)
        outs = llm.generate(
            prompt_token_ids=[list(p) for p in PROMPTS],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=24,
                                           ignore_eos=True))
        return ([o.output_token_ids for o in outs],
                llm.scheduler.num_preemptions)

    want, base_preempt = run(False)
    got, _ = run(True)
    assert got == want, (got, want)
    assert base_preempt >= 0          # pool small enough to be tight


# ---- rejection sampling + adaptive k (VERDICT r03 weak #4 / next #6) -------

def _spec_distribution_l1(llm, base, n_runs, n_tok):
    """Aggregate next-token histograms over ``n_runs`` seeded runs of a
    draft-friendly prompt, L1-compared between the two engines. Seeded
    engines are run-to-run deterministic, so the statistic itself is
    deterministic for a fixed (checkpoint, n_runs) — only the TOLERANCE
    needs a statistical argument (see callers)."""
    import collections

    prompt = [5, 9, 5, 9, 5, 9, 5, 9]          # (5,9) pattern → drafts fire

    def histogram(engine):
        # one batched generate: n_runs seeded requests of the same prompt
        sps = [SamplingParams(temperature=1.0, seed=s, max_tokens=n_tok,
                              ignore_eos=True) for s in range(n_runs)]
        outs = engine.generate(
            prompt_token_ids=[list(prompt) for _ in range(n_runs)],
            sampling_params=sps)
        h = collections.Counter()
        for out in outs:
            h.update(out.output_token_ids)
        return h

    h_spec, h_base = histogram(llm), histogram(base)
    total = n_runs * n_tok
    support = set(h_spec) | set(h_base)
    l1 = sum(abs(h_spec[t] - h_base[t]) for t in support) / total
    return l1, len(support), total, (h_spec, h_base)


def _l1_tolerance(support: int, total: int) -> float:
    """Deterministic tolerance DERIVED from the run count instead of a
    hand-tuned constant (the old fixed 0.35 was environment-flaky: the
    two engines consume different draw indices, so the statistic shifts
    with BLAS/threading numerics). The expected L1 distance between two
    independent empirical draws of the same distribution is bounded by
    E[L1] <= sqrt(2·support/total) (per-token binomial std, summed by
    Cauchy-Schwarz); 2x that plus a small floor rejects a wrong residual
    distribution (which lands near the distributions' true L1, an O(1)
    constant) while absorbing sampling noise at any run count."""
    import math
    return 2.0 * math.sqrt(2.0 * support / total) + 0.05


def test_spec_sampled_distribution_preserved(ckpt):
    """Rejection sampling against the one-hot prompt-lookup proposal must
    preserve the target distribution: aggregate next-token histograms
    over seeded runs match between the spec and non-spec engines on a
    draft-friendly (repetitive) prompt. Fast arm — the 120-run tighter
    check is the ``slow``-marked test below."""
    llm = make_llm(ckpt, spec=True)
    base = make_llm(ckpt)
    l1, support, total, hists = _spec_distribution_l1(llm, base, 40, 6)
    assert llm.scheduler.spec_stats["proposed"] > 0
    tol = _l1_tolerance(support, total)
    assert l1 < tol, f"L1 {l1:.3f} >= tol {tol:.3f} ({hists})"


@pytest.mark.slow
def test_spec_sampled_distribution_preserved_heavy(ckpt):
    """120-run arm of the distribution oracle: more samples shrink both
    the statistic and its derived tolerance."""
    llm = make_llm(ckpt, spec=True)
    base = make_llm(ckpt)
    l1, support, total, hists = _spec_distribution_l1(llm, base, 120, 6)
    assert llm.scheduler.spec_stats["proposed"] > 0
    tol = _l1_tolerance(support, total)
    assert l1 < tol, f"L1 {l1:.3f} >= tol {tol:.3f} ({hists})"


def test_spec_sampled_seeded_deterministic(ckpt):
    """spec_ngram=1 + a prompt covering the whole vocab: every sampled
    continuation token has an earlier occurrence, so drafts fire on
    (almost) every decode step — and the seeded run is reproducible."""
    def spec1_llm():
        return LLM(config=EngineConfig(
            model=ckpt, dtype="float32", max_model_len=256,
            spec_decode="ngram", spec_k=4, spec_ngram=1,
            cache=CacheConfig(page_size=4, num_pages=128)))

    llm1, llm2 = spec1_llm(), spec1_llm()
    sp = SamplingParams(temperature=0.9, seed=11, max_tokens=24,
                        ignore_eos=True)
    p = list(range(1, 120))
    a = llm1.generate(prompt_token_ids=[list(p)], sampling_params=sp)[0]
    b = llm2.generate(prompt_token_ids=[list(p)], sampling_params=sp)[0]
    assert a.output_token_ids == b.output_token_ids
    assert llm1.scheduler.spec_stats["proposed"] > 0


def test_adaptive_k_collapses_and_regrows():
    """AIMD draft length: zero-accepted runs collapse a seq's k to 1; full
    sweeps grow it back one per step up to spec_k."""
    from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
    from gllm_tpu.memory_manager import make_memory_manager
    from gllm_tpu.scheduler import ScheduledBatch, ScheduledSeq, Scheduler
    from gllm_tpu.sequence import Sequence

    cfg = EngineConfig(load_format="dummy", max_model_len=256,
                       spec_decode="ngram", spec_k=4, spec_ngram=2,
                       scheduler=SchedulerConfig(),
                       cache=CacheConfig(page_size=4, num_pages=64))
    mm = make_memory_manager(64, 4, False)
    sched = Scheduler(cfg, mm)
    sched.spec_cfg = (cfg.spec_ngram, cfg.spec_k)

    seq = Sequence(0, [5, 9, 5, 9, 5, 9], SamplingParams(
        temperature=0.0, max_tokens=64, ignore_eos=True))
    sched.add_seq(seq)
    batch = sched.schedule_once()          # prefill
    sched.process_output(batch, [5], frozenset())

    # decode with drafts proposed from the (5,9) pattern
    batch = sched.schedule_once()
    it = batch.items[0]
    assert it.draft_tokens, "repetitive prompt must draft"
    k0 = len(it.draft_tokens)
    # simulate ZERO accepted: only the correction token committed
    sched.process_output_multi(batch, [[7]], frozenset())
    assert seq.spec_k_cur == 1

    # next proposal respects the collapsed k; simulate FULL sweeps after
    # it (commit every draft + a continuation that keeps the 5/9 pattern
    # alive so later proposals keep firing): k grows one per step to cap
    first = True
    for _ in range(8):
        batch = sched.schedule_once()
        it = batch.items[0]
        d = len(it.draft_tokens)
        if first:
            assert d <= 1, d
            first = False
        last = seq.token_ids[-1]
        nxt = 9 if last == 5 else 5
        toks = (list(it.draft_tokens)
                + [9 if it.draft_tokens[-1] == 5 else 5]) if d else [nxt]
        sched.process_output_multi(batch, [toks], frozenset())
    assert seq.spec_k_cur == cfg.spec_k, seq.spec_k_cur
    assert k0 <= cfg.spec_k


def test_spec_under_pp2_penalties_and_logprobs(ckpt):
    """The pp last-stage verify applies the same draft-prefix logit
    adjustments and emits spec logprobs — penalized/bias/logprobs
    requests stay byte-identical to the single-stage plain engine."""
    from gllm_tpu.config import ParallelConfig
    sp = SamplingParams(temperature=0.0, max_tokens=16, ignore_eos=True,
                        repetition_penalty=1.3, presence_penalty=0.4,
                        logit_bias={7: 2.5}, logprobs=2)
    base = make_llm(ckpt)
    b = base.generate(prompt_token_ids=[list(PROMPTS[0])],
                      sampling_params=sp)[0]
    del base
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=256,
        spec_decode="ngram", spec_k=4, spec_ngram=2,
        cache=CacheConfig(page_size=4, num_pages=128),
        parallel=ParallelConfig(pp=2))
    llm = LLM(config=cfg)
    a = llm.generate(prompt_token_ids=[list(PROMPTS[0])],
                     sampling_params=sp)[0]
    assert a.output_token_ids == b.output_token_ids
    assert llm.scheduler.spec_stats["proposed"] > 0
    assert a.logprobs is not None and len(a.logprobs) == len(b.logprobs)
    for (ca, ia, va), (cb, ib, vb) in zip(a.logprobs, b.logprobs):
        assert ia == ib
        np.testing.assert_allclose(ca, cb, rtol=2e-4, atol=2e-5)


def test_spec_under_overlap_scheduling(ckpt):
    """Overlap scheduling no longer disables speculation: draft batches
    dispatch synchronously (their commit count is device-decided) while
    non-spec steps keep chaining — greedy outputs stay byte-identical and
    drafts are actually proposed."""
    base = make_llm(ckpt)
    want = greedy(base, PROMPTS)
    del base
    llm = make_llm(ckpt, spec=True, overlap_scheduling=True,
                   overlap_depth=2)
    got = greedy(llm, PROMPTS)
    assert got == want, (got, want)
    st = llm.scheduler.spec_stats
    assert st["proposed"] > 0 and st["accepted"] > 0


def test_spec_under_overlap_multi_step(ckpt):
    """Spec + overlap + fused multi-step decode coexist: spec batches are
    excluded from fused chains but the engine stays byte-identical."""
    base = make_llm(ckpt)
    want = greedy(base, PROMPTS)
    del base
    llm = make_llm(ckpt, spec=True, overlap_scheduling=True,
                   overlap_depth=2, multi_step_decode=4)
    got = greedy(llm, PROMPTS)
    assert got == want, (got, want)
    assert llm.scheduler.spec_stats["proposed"] > 0
