"""Incremental detokenization.

Streaming-safe decode: the reference tracks per-sequence offsets and only
emits text once it is not a partial multi-byte sequence
(/root/reference/gllm/sequence.py detokenize_inc). Standard two-offset
algorithm: ``prefix_offset`` marks the start of the token window used for
context, ``read_offset`` the first token whose text has not been emitted.
"""

from __future__ import annotations

from typing import List, Tuple

REPLACEMENT = "�"


def detokenize_incrementally(
    tokenizer,
    token_ids: List[int],
    prefix_offset: int,
    read_offset: int,
    end: int = None,
) -> Tuple[str, int, int]:
    """Returns (new_text, new_prefix_offset, new_read_offset).

    ``end`` bounds the token window (default: all of ``token_ids``) —
    callers replaying a multi-token commit one token at a time pass it
    instead of slicing the full list per token."""
    if end is None:
        end = len(token_ids)
    prefix_text = tokenizer.decode(token_ids[prefix_offset:read_offset],
                                   skip_special_tokens=False)
    full_text = tokenizer.decode(token_ids[prefix_offset:end],
                                 skip_special_tokens=False)
    if len(full_text) > len(prefix_text) and not full_text.endswith(
            REPLACEMENT):
        return (full_text[len(prefix_text):],
                read_offset, end)
    return "", prefix_offset, read_offset
