"""Pipelined engine loop: in-flight entries and the FutureMap.

TPU-native analogue of the reference OverlapWorker/FutureMap pair
(PAPER.md §4-5): the reference resolves negative placeholder token ids
against a future table when the GPU step lands; here the placeholder IS
the device array — a re-formed batch's input tokens are spliced from the
previous entry's on-device sampled tokens (runner._splice_mapped_tokens)
and the host only tracks *which sequences were promised alive*.

The promise contract (docs/overlap_scheduling.md#pipelined-loop):

- Scheduling needs token COUNTS, not values: page allocation, positions,
  slots, and the sampling out_step all derive from the promised frontier
  ``computed_before + num_new_tokens`` of a sequence's newest in-flight
  row (scheduler.schedule_reform).
- Deaths the host can predict (LENGTH: max_tokens / max_model_len) are
  applied at promise time — those rows simply drop, and no divergence is
  possible. Deaths the host cannot predict (EOS / stop tokens / stop
  strings) are assumed NOT to happen.
- When a finish commits for a sequence some later in-flight entry
  promised alive, that entry — and every entry chained off it — is
  INVALIDATED: its sampled tokens never commit, its in-flight counts
  unwind (scheduler.discard_batch), and the sync path rebuilds from
  committed state. Greedy and seeded sampling draw identically on the
  rebuild (context- resp. (seed, out_step)-determined), so token streams
  stay byte-identical to the sync loop.

No jax imports: this module is host bookkeeping only.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class InFlight:
    """One dispatched-but-uncollected engine entry.

    ``batch`` is a ScheduledBatch or a fused-chain list of them;
    ``handle`` is the runner's opaque async handle; ``t_dispatch`` and
    ``phases`` feed the attribution layer (obs/spans.py). The pipelined
    fields: ``chained`` marks entries whose input tokens came off the
    previous decode entry's device array (chain extensions, fused
    blocks, re-forms) — an invalidation cascades through them;
    ``roots`` marks a sync-scheduled entry that ROOTS a fresh chain
    from host-committed state (a pure-decode sync batch or a fresh
    fused block) — the cascade stops there, later chained entries
    descend from it, not from anything older; ``promises`` is the set
    of seq ids a speculative re-form assumed alive; ``invalid`` marks
    an entry reconciliation dropped (collected as a discard, never
    committed)."""

    batch: object
    handle: object
    t_dispatch: float
    phases: Optional[dict]
    chained: bool = False
    roots: bool = False
    promises: frozenset = frozenset()
    invalid: bool = False

    @property
    def tip(self):
        """(batch, handle) — the chain-tip view the fill loop extends."""
        return self.batch, self.handle


@dataclasses.dataclass
class DPBatches:
    """Per-replica batch list for one dp SUPER-STEP entry (the dp
    pipelined loop, docs/overlap_scheduling.md#topology-matrix):
    ``batches[r]`` is replica r's ScheduledBatch or None (idle dummy).
    A dedicated holder — NOT a plain list — so the fused-chain
    ``isinstance(batch, list)`` checks elsewhere never mistake a
    dp-wide entry for a multi-step chain."""

    batches: list


class FutureMap:
    """Promise registry + reconciliation for the pipelined loop.

    State lives IN the in-flight entries (promises travel with the work
    they gate); this object owns the reconciliation scan and the
    divergence counters the loop_stall observability reads."""

    def __init__(self):
        self.rebuilds = 0          # invalidated entries, lifetime
        self.divergences = 0       # reconcile() calls that invalidated

    @staticmethod
    def promised_ids(batch) -> frozenset:
        """Seq ids a re-formed batch assumed alive: rows whose input
        token is a promise (src_rows >= 0). Joining rows (src -1) carry
        committed state — nothing is assumed for them."""
        if batch.src_rows is None:
            return frozenset()
        return frozenset(it.seq.seq_id
                         for it, src in zip(batch.items, batch.src_rows)
                         if src >= 0)

    def reconcile(self, in_flight, finished_ids) -> int:
        """Invalidate every in-flight entry whose promises intersect
        ``finished_ids`` — and, transitively, every later entry chained
        off an invalidated one (its input tokens came from a batch that
        never commits). Entries scheduled synchronously from committed
        state stay valid — interleaved prefill dispatches because their
        sequences were not in flight when formed, and a later
        chain-ROOTING entry (``roots``) additionally STOPS the cascade:
        chained entries after it descend from that valid root, not from
        the invalidated speculation, and discarding them would re-run
        real committed-parent work for nothing. Returns the number of
        entries newly invalidated."""
        if not finished_ids:
            return 0
        hit = 0
        cascading = False
        for e in in_flight:
            if e.invalid:
                cascading = True
                continue
            if (e.promises & finished_ids) or (cascading and e.chained):
                e.invalid = True
                cascading = True
                hit += 1
                continue
            if e.roots:
                # a valid sync-rooted decode batch: later chained
                # entries extend IT — the invalidation stops here
                cascading = False
        self.rebuilds += hit
        if hit:
            self.divergences += 1
        return hit

    @staticmethod
    def trim_overpromise(in_flight, frontiers) -> int:
        """Fused speculation (config.spec_fused): a spec block's chained
        descendants were scheduled off worst-case token-count UPPER
        bounds (every sub-step may emit spec_k+1 tokens); when the block
        collects, the committed counts are known and any over-promise is
        trimmed — each still-in-flight spec entry's per-link
        ``computed_before`` values rebase onto the committed frontier.

        This is pure host bookkeeping: the device already carries the
        ACTUAL frontier across blocks (the spec state in the handle), so
        the trim never touches token content — it tightens the
        allocation/feasibility arithmetic later ``schedule_chain``
        extensions run off these entries' items, exactly the
        invalidate-and-rebuild discipline's bookkeeping half.

        ``frontiers`` maps seq_id → committed ``num_computed_tokens``.
        Returns the total number of over-promised tokens trimmed.

        Descendant entries rebase by the SAME per-seq delta as the
        oldest in-flight entry: the over-promise accrued exactly once at
        the collected block's boundary, and the later entries' strides
        (scheduled relative to their parent) remain upper bounds of
        whatever the parent actually emits — collapsing them all onto
        the committed frontier would UNDER-bound page needs."""
        trimmed = 0
        applied = {}        # seq_id -> delta fixed at the oldest entry
        for e in in_flight:
            if e.invalid or not e.chained:
                continue
            chain = e.batch if isinstance(e.batch, list) else [e.batch]
            if not getattr(chain[0], "spec_block", False):
                continue
            deltas = {}
            for it in chain[0].items:
                sid = it.seq.seq_id
                if sid not in applied:
                    f = frontiers.get(sid)
                    if f is None:
                        continue
                    # anchor at the OLDEST entry even when the delta is
                    # zero — descendants must never re-derive their own
                    # (their elevation over the committed frontier is
                    # their parent's still-unknown emission, not an
                    # over-promise)
                    applied[sid] = max(0, it.computed_before - f)
                    trimmed += applied[sid]
                if applied[sid]:
                    deltas[sid] = applied[sid]
            if not deltas:
                continue
            for b in chain:
                for it in b.items:
                    d = deltas.get(it.seq.seq_id)
                    if d:
                        it.computed_before -= d
        return trimmed
