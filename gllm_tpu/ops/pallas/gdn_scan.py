"""Pallas TPU kernel for the gated-delta-rule chunk scan.

TPU-native replacement for the sequential half of the reference's fla
Triton suite (/root/reference/gllm/layers/ops/fla/ — chunk.py's
fwd_recompute/fwd_o pipeline): the in-chunk triangular work (decay
matrices, (I+A)^-1, v', k_cumdecay) is MXU-friendly *parallel* math that
XLA already batches well (native TriangularSolve), so it stays in
ops/gdn.py; what XLA cannot do well is the *sequential* inter-chunk state
recurrence — a lax.scan whose [Dk, Dv] carry round-trips HBM every chunk.

This kernel fuses that scan: grid = (S·H, N) with the chunk axis innermost
("arbitrary" semantics), the running state lives in VMEM scratch across
chunk steps, and per-chunk operand blocks stream through the Pallas
pipeline (double-buffered DMA). HBM traffic for the state drops from
2·N·Dk·Dv·4 bytes per (seq, head) to one final write.

Recurrence per chunk (HF torch_chunk_gated_delta_rule semantics,
precomputed operands):
    v'   = k_cumdecay @ state
    vnew = v2 - v'
    out  = (q ⊙ e^g) @ state + attn_local @ vnew
    state = e^{g_C} · state + (k ⊙ e^{g_C - g})ᵀ @ vnew
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gllm_tpu.ops.pallas.paged_kv import CompilerParams


def _kernel(q_ref, k_ref, v2_ref, kcd_ref, attn_ref, g_ref, init_ref,
            out_ref, final_ref, state, *, chunk: int):
    n = pl.program_id(1)

    @pl.when(n == 0)
    def _():
        state[:] = init_ref[0]

    st = state[:]                                       # [Dk, Dv] f32
    g = g_ref[0, 0]                                     # [C, 1]
    eg = jnp.exp(g)
    v_new = v2_ref[0, 0] - jax.lax.dot(                 # [C, Dv]
        kcd_ref[0, 0], st, preferred_element_type=jnp.float32)
    out = jax.lax.dot(q_ref[0, 0] * eg, st,
                      preferred_element_type=jnp.float32) \
        + jax.lax.dot(attn_ref[0, 0], v_new,
                      preferred_element_type=jnp.float32)
    g_last = g[chunk - 1, 0]
    k_dec = k_ref[0, 0] * jnp.exp(g_last - g)           # [C, Dk]
    st = st * jnp.exp(g_last) + jax.lax.dot(
        k_dec.T, v_new, preferred_element_type=jnp.float32)
    state[:] = st
    out_ref[0, 0] = out
    final_ref[0] = st


@functools.partial(jax.jit, static_argnames=("interpret",))
def gdn_chunk_scan(
    qc: jnp.ndarray,      # [B, N, C, Dk] f32 (l2normed, scaled)
    kc: jnp.ndarray,      # [B, N, C, Dk] f32
    v2: jnp.ndarray,      # [B, N, C, Dv] f32 (Tmat @ v_beta)
    kcd: jnp.ndarray,     # [B, N, C, Dk] f32 (Tmat @ (k_beta · e^gcum))
    attn: jnp.ndarray,    # [B, N, C, C]  f32 (masked local scores)
    gcum: jnp.ndarray,    # [B, N, C, 1]  f32 (in-chunk cumulative decay)
    init_state: jnp.ndarray,   # [B, Dk, Dv] f32
    *,
    interpret: bool = False,
):
    """Returns (out [B, N, C, Dv] f32, final_state [B, Dk, Dv] f32)."""
    B, N, C, Dk = qc.shape
    Dv = v2.shape[-1]

    def blk(shape_tail):
        return pl.BlockSpec((1, 1) + shape_tail,
                            lambda b, n: (b, n) + (0,) * len(shape_tail),
                            memory_space=pltpu.VMEM)

    state_spec = pl.BlockSpec((1, Dk, Dv), lambda b, n: (b, 0, 0),
                              memory_space=pltpu.VMEM)
    out, final = pl.pallas_call(
        functools.partial(_kernel, chunk=C),
        grid=(B, N),
        in_specs=[blk((C, Dk)), blk((C, Dk)), blk((C, Dv)), blk((C, Dk)),
                  blk((C, C)), blk((C, 1)), state_spec],
        out_specs=[blk((C, Dv)), state_spec],
        out_shape=[jax.ShapeDtypeStruct((B, N, C, Dv), jnp.float32),
                   jax.ShapeDtypeStruct((B, Dk, Dv), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((Dk, Dv), jnp.float32)],
        # chunk axis is a sequential scan over the VMEM-resident state;
        # the batch axis is embarrassingly parallel
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(qc, kc, v2, kcd, attn, gcum, init_state)
    return out, final
