"""Pallas TPU paged decode attention.

NOTE (unified step, docs/overlap_scheduling.md#unified-step): under
``--unified-step`` every paged step — pure decode included — routes
through the unified ragged kernel (ops/pallas/ragged_attention.py,
``unified=True``), whose decode-class blocks reproduce this kernel's
grouped round-robin fetch discipline inside the one program. This module
is kept as the legacy dispatch path (flag off) and as the PARITY ORACLE
the unified kernel's decode-class path is tested against
(tests/test_unified_step.py).

The decode half of the reference's core attention kernel
(sgl_kernel ``flash_attn_with_kvcache`` — /root/reference/gllm/layers/
attention.py:92-140; Triton split-K analogue in layers/ops/
triton_decode_attention.py). One query row per sequence attends over that
sequence's paged KV context.

Design (TPU-first, not a Triton translation):
- grid = (S,): one program per sequence; each program streams its own page
  list — HBM traffic is the sequence's *actual* context, independent of the
  padded page-table bucket (the XLA gather fallback pays the padded extent).
- KV pages stay in HBM (`pl.ANY`); the kernel double-buffers page blocks
  into VMEM with async DMA, overlapping fetch with the flash-attention
  accumulation (online softmax in f32 carried through the kv-block loop).
- GQA is computed as a kv-head-batched dot: q reshaped to [Hkv, G, D] so
  every kv head's group hits the MXU together.
- The kv-block loop bound is dynamic (ceil(kv_len / block)): padded
  sequences (kv_len 0) skip the loop entirely.
- MLA absorbed mode: ``v_cache=None`` + ``v_dim`` reads values as the
  leading ``v_dim`` lanes of each key block (the latent prefix) — one DMA
  stream instead of two (reference MLA shares the latent cache the same
  way, layers/attention.py:272-293).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gllm_tpu.ops.pallas.paged_kv import (CompilerParams, attend_block,
                                          kv_stream_specs, make_fetch_fns,
                                          unpack_refs)

DEFAULT_KV_BLOCK = 256


def _kernel_grouped(kv_lens_ref, pt_ref,    # scalar prefetch
                    *refs,
                    page_size: int, pages_per_block: int, scale: float,
                    num_kv_heads: int, group: int, head_dim: int,
                    v_dim: int, shared_kv: bool, mqa: bool, gsz: int,
                    quant: bool):
    """``gsz`` sequences per grid program, ONE buffer slot each, fetched
    round-robin so up to ``gsz`` page DMAs are in flight at once.

    Rationale (r5 on-chip): decode compute per kv block is ~0 — the MXU
    dots are microscopic — so the per-seq double buffer of ``_kernel``
    degenerates into a chain of bare DMA *latencies* (~44 µs/seq
    measured; × S/2 programs per core × num_layers ≈ the whole decode
    step). Interleaving ``gsz`` sequences divides that latency chain by
    ``gsz`` without paying any padded-extent HBM traffic."""
    (q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf,
     vs_buf, sems) = unpack_refs(refs, shared_kv, quant)
    gi = pl.program_id(0)
    bk = pages_per_block * page_size
    start_fetch, wait_fetch = make_fetch_fns(
        pt_ref, k_hbm, v_hbm, k_buf, v_buf, sems, pages_per_block,
        shared_kv, ks_hbm=ks_hbm, vs_hbm=vs_hbm, ks_buf=ks_buf,
        vs_buf=vs_buf)

    seq_ids = [gi * gsz + g for g in range(gsz)]
    kv_lens = [kv_lens_ref[s] for s in seq_ids]
    n_blocks = [pl.cdiv(kv_len, bk) for kv_len in kv_lens]
    for g in range(gsz):
        @pl.when(n_blocks[g] > 0)
        def _(g=g):
            start_fetch(g, seq_ids[g], 0)

    lead = (num_kv_heads * group,) if mqa else (num_kv_heads, group)
    qs = []
    for g in range(gsz):
        q = q_ref[g].astype(jnp.float32) * scale          # [Hq, D]
        qs.append(q if mqa else q.reshape(num_kv_heads, group, head_dim))

    max_nb = n_blocks[0]
    for g in range(1, gsz):
        max_nb = jnp.maximum(max_nb, n_blocks[g])

    def body(r, carry):
        out = list(carry)
        for g in range(gsz):
            m, l, acc = out[3 * g], out[3 * g + 1], out[3 * g + 2]
            live = r < n_blocks[g]

            @pl.when(live)
            def _(g=g):
                wait_fetch(g, seq_ids[g], r)

            # NOTE: the next-block re-issue for this slot happens inside
            # pl.when below, between the (buffered) loads attend_block
            # performs and the rest of the round-robin — program order
            # keeps the loads ahead of the re-issued DMA.
            m_new, l_new, acc_new = attend_block(
                qs[g], k_buf, v_buf, g, bk, num_kv_heads, head_dim,
                v_dim, shared_kv, mqa, kv_lens[g], r, m, l, acc,
                ks_buf=ks_buf, vs_buf=vs_buf)

            @pl.when(live & (r + 1 < n_blocks[g]))
            def _(g=g):
                start_fetch(g, seq_ids[g], r + 1)

            out[3 * g] = jnp.where(live, m_new, m)
            out[3 * g + 1] = jnp.where(live, l_new, l)
            out[3 * g + 2] = jnp.where(live, acc_new, acc)
        return tuple(out)

    init = []
    for _ in range(gsz):
        init += [jnp.full((*lead, 1), -jnp.inf, jnp.float32),
                 jnp.zeros((*lead, 1), jnp.float32),
                 jnp.zeros((*lead, v_dim), jnp.float32)]
    final = jax.lax.fori_loop(0, max_nb, body, tuple(init))
    for g in range(gsz):
        l, acc = final[3 * g + 1], final[3 * g + 2]
        out = acc / jnp.maximum(l, 1e-30)                # padded seqs → 0
        o_ref[g] = out.reshape(num_kv_heads * group,
                               v_dim).astype(o_ref.dtype)


def _kernel(kv_lens_ref, pt_ref,            # scalar prefetch
            *refs,
            page_size: int, pages_per_block: int, scale: float,
            num_kv_heads: int, group: int, head_dim: int, v_dim: int,
            shared_kv: bool, mqa: bool, quant: bool):
    (q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf,
     vs_buf, sems) = unpack_refs(refs, shared_kv, quant)
    s = pl.program_id(0)
    kv_len = kv_lens_ref[s]
    bk = pages_per_block * page_size
    n_blocks = pl.cdiv(kv_len, bk)

    start_fetch, wait_fetch = make_fetch_fns(
        pt_ref, k_hbm, v_hbm, k_buf, v_buf, sems, pages_per_block,
        shared_kv, ks_hbm=ks_hbm, vs_hbm=vs_hbm, ks_buf=ks_buf,
        vs_buf=vs_buf)

    @pl.when(n_blocks > 0)
    def _():
        start_fetch(0, s, 0)

    q = q_ref[0].astype(jnp.float32) * scale          # [Hq, D]
    # MQA (Hkv == 1): keep everything 2-D — scores [Hq, BK] from one
    # q @ kᵀ MXU dot; the caches arrive 3-D with the head axis squeezed.
    qh = q if mqa else q.reshape(num_kv_heads, group, head_dim)

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_blocks)
        def _():
            start_fetch(1 - slot, s, i + 1)

        wait_fetch(slot, s, i)
        return attend_block(qh, k_buf, v_buf, slot, bk, num_kv_heads,
                            head_dim, v_dim, shared_kv, mqa, kv_len, i,
                            m, l, acc, ks_buf=ks_buf, vs_buf=vs_buf)

    lead = (num_kv_heads * group,) if mqa else (num_kv_heads, group)
    m0 = jnp.full((*lead, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((*lead, 1), jnp.float32)
    acc0 = jnp.zeros((*lead, v_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)                   # padded seqs → 0
    o_ref[0] = out.reshape(num_kv_heads * group,
                           v_dim).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "kv_block", "interpret",
                                    "v_dim", "group_size"))
def paged_decode_attention(
    q: jnp.ndarray,            # [S, Hq, D]
    k_cache: jnp.ndarray,      # [num_pages, page_size, Hkv, D]
    v_cache: Optional[jnp.ndarray],  # None → v = k[..., :v_dim] (MLA)
    kv_lens: jnp.ndarray,      # [S] int32 (0 for padded rows)
    page_table: jnp.ndarray,   # [S, max_pages] int32 (padding → dummy page 0)
    *,
    scale: float,
    kv_block: int = DEFAULT_KV_BLOCK,
    interpret: bool = False,
    v_dim: Optional[int] = None,
    group_size: int = 1,       # seqs per grid program (see _kernel_grouped)
    k_scale: Optional[jnp.ndarray] = None,   # [num_pages, Hkv] f32 (int8)
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    S, num_q_heads, head_dim = q.shape
    num_pages, page_size, num_kv_heads, _ = k_cache.shape
    max_pages = page_table.shape[1]
    group = num_q_heads // num_kv_heads
    shared_kv = v_cache is None
    quant = k_scale is not None
    if shared_kv:
        if v_dim is None:
            raise ValueError("v_dim required when v_cache is None")
    else:
        v_dim = v_cache.shape[-1]

    # MQA (MLA's latent cache): squeeze the singleton head axis — Mosaic's
    # sublane tiling rejects slicing a size-1 second-minor dim — and run
    # the kernel's 2-D path.
    mqa = num_kv_heads == 1
    if quant and (mqa or shared_kv):
        raise NotImplementedError(
            "int8 KV cache unsupported for MQA/MLA decode kernels")
    if mqa:
        k_cache = k_cache.reshape(num_pages, page_size, head_dim)
        if v_cache is not None:
            v_cache = v_cache.reshape(num_pages, page_size, v_dim)

    pages_per_block = max(1, min(kv_block // page_size, max_pages))
    # page_table must cover whole blocks; pad with dummy page 0.
    rem = max_pages % pages_per_block
    if rem:
        page_table = jnp.pad(page_table,
                             ((0, 0), (0, pages_per_block - rem)))
        max_pages += pages_per_block - rem

    gsz = max(1, group_size)
    if gsz > 1:
        # pad the seq axis to a whole number of groups; padded rows have
        # kv_len 0 (skip every round) and dummy page-table rows
        s_pad = -(-S // gsz) * gsz
        if s_pad != S:
            q = jnp.pad(q, ((0, s_pad - S), (0, 0), (0, 0)))
            kv_lens = jnp.pad(kv_lens, (0, s_pad - S))
            page_table = jnp.pad(page_table, ((0, s_pad - S), (0, 0)))
        kernel = functools.partial(
            _kernel_grouped, page_size=page_size,
            pages_per_block=pages_per_block, scale=scale,
            num_kv_heads=num_kv_heads, group=group, head_dim=head_dim,
            v_dim=v_dim, shared_kv=shared_kv, mqa=mqa, gsz=gsz,
            quant=quant)
        slots, n_prog, blk = gsz, s_pad // gsz, gsz
    else:
        kernel = functools.partial(
            _kernel, page_size=page_size, pages_per_block=pages_per_block,
            scale=scale, num_kv_heads=num_kv_heads, group=group,
            head_dim=head_dim, v_dim=v_dim, shared_kv=shared_kv, mqa=mqa,
            quant=quant)
        slots, n_prog, blk = 2, S, 1
        s_pad = S

    kv_specs, scratch_shapes, kv_inputs = kv_stream_specs(
        k_cache, v_cache, pages_per_block, page_size, num_kv_heads,
        head_dim, v_dim, mqa=mqa, slots=slots, k_scale=k_scale,
        v_scale=v_scale)
    in_specs = [
        pl.BlockSpec((blk, num_q_heads, head_dim), lambda s, *_: (s, 0, 0),
                     memory_space=pltpu.VMEM),
    ] + kv_specs
    inputs = [kv_lens, page_table, q] + kv_inputs

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_prog,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((blk, num_q_heads, v_dim),
                               lambda s, *_: (s, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=scratch_shapes,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_pad, num_q_heads, v_dim),
                                       q.dtype),
        # Sequences/groups are independent → let Mosaic split the grid
        # across Megacore TensorCores.
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)) if interpret else
        CompilerParams(dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*inputs)
    return out[:S] if s_pad != S else out
