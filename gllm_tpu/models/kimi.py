"""Kimi K2.5: MoonViT3d vision tower + DeepSeek-V3 language backbone.

Reference: /root/reference/gllm/models/kimi_k25.py (311 LoC) +
kimi_k25_vision.py (475 LoC). The LM half IS our DeepSeek decoder
(gllm_tpu/models/deepseek.py — MLA latent cache, noaux_tc routing);
positions are plain 1-D (no mrope). The tower lives in
gllm_tpu/models/kimi_vision.py; the media placeholder
(``media_placeholder_token_id``, outside the LM vocab) marks visual rows
that the embedding splice overwrites.

Placeholder expansion contract: Kimi's chat template emits ONE
``<|media_pad|>`` per image; the intake path expands it to the item's
merged-token count (``(h//kh)·(w//kw)``, frame-independent — temporal
pooling collapses t) before the engine sees the prompt, mirroring the
reference ``build_kimi_input_ids``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from gllm_tpu.models import deepseek, kimi_vision
from gllm_tpu.models.config import ModelConfig

init_kv_cache = deepseek.init_kv_cache
compute_logits = deepseek.compute_logits
forward = deepseek.forward
make_rope_table = deepseek.make_rope_table


def vision_cfg(cfg: ModelConfig) -> kimi_vision.KimiVisionConfig:
    assert cfg.vision_config is not None
    return kimi_vision.from_hf_vision_config(cfg.vision_config,
                                             cfg.hidden_size)


def init_params(cfg: ModelConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> dict:
    params = deepseek.init_params(cfg, seed=seed, dtype=dtype)
    params["visual"] = kimi_vision.init_vision_params(vision_cfg(cfg),
                                                     seed=seed, dtype=dtype)
    return params


def embed_mm(params, cfg: ModelConfig, pixels, grid_thw) -> jnp.ndarray:
    return kimi_vision.embed_single(params["visual"], vision_cfg(cfg),
                                    pixels, grid_thw)


def num_vis_tokens(cfg: ModelConfig, grid_thw) -> int:
    """Merged tokens per item: spatial only (temporal pooling)."""
    kh, kw = vision_cfg(cfg).merge_kernel
    _, h, w = (int(v) for v in grid_thw)
    return (h // kh) * (w // kw)


def _kimi_rules(cfg: ModelConfig):
    from gllm_tpu.models.loader import deepseek_rules
    base = deepseek_rules(cfg)
    vcfg = vision_cfg(cfg)

    blk = {
        "norm0.weight": ("norm0_w", None), "norm0.bias": ("norm0_b", None),
        "norm1.weight": ("norm1_w", None), "norm1.bias": ("norm1_b", None),
        "wqkv.weight": ("wqkv_w", "t"), "wqkv.bias": ("wqkv_b", None),
        "wo.weight": ("wo_w", "t"), "wo.bias": ("wo_b", None),
        "mlp.fc0.weight": ("fc0_w", "t"), "mlp.fc0.bias": ("fc0_b", None),
        "mlp.fc1.weight": ("fc1_w", "t"), "mlp.fc1.bias": ("fc1_b", None),
    }
    merger = {
        "pre_norm.weight": ("pre_norm_w", None),
        "pre_norm.bias": ("pre_norm_b", None),
        "proj.0.weight": ("fc1_w", "t"), "proj.0.bias": ("fc1_b", None),
        "proj.2.weight": ("fc2_w", "t"), "proj.2.bias": ("fc2_b", None),
    }

    def patch_tf(t: np.ndarray) -> dict:
        # Conv2d [C, 3, ps, ps] → flattened [3·ps², C] matmul
        return {"patch_w": t.reshape(vcfg.hidden_size, -1).T}

    def rule(name: str):
        if name.startswith("language_model."):
            return base(name[len("language_model."):])
        if name.startswith("vision_tower."):
            rest = name[len("vision_tower."):]
            if rest == "patch_embed.proj.weight":
                return (("visual", "__multi__"), None, patch_tf)
            if rest == "patch_embed.proj.bias":
                return (("visual", "patch_b"), None, None)
            if rest == "patch_embed.pos_emb.weight":
                return (("visual", "pos_emb"), None, None)
            if rest == "encoder.final_layernorm.weight":
                return (("visual", "final_ln_w"), None, None)
            if rest == "encoder.final_layernorm.bias":
                return (("visual", "final_ln_b"), None, None)
            if rest.startswith("encoder.blocks."):
                idx_s, _, leaf = \
                    rest[len("encoder.blocks."):].partition(".")
                if leaf in blk:
                    target, tf = blk[leaf]
                    return (("visual", "blocks", target), int(idx_s), tf)
            return None
        if name.startswith("mm_projector."):
            leaf = name[len("mm_projector."):]
            if leaf in merger:
                target, tf = merger[leaf]
                return (("visual", "merger", target), None, tf)
            return None
        return base(name)

    return rule


def load_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16,
                progress_cb=None, skip_visual: bool = False) -> dict:
    from gllm_tpu.models.loader import _load_params
    template = jax.eval_shape(lambda: init_params(cfg, dtype=dtype))
    return _load_params(model_dir, template, _kimi_rules(cfg),
                        progress_cb, skip_visual=skip_visual)
