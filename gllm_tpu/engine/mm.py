"""Multimodal request state: hashing, mrope positions, visual-row indexing.

Host-side half of the reference's MM pipeline
(/root/reference/gllm/model_runner.py:100-158,663-1406): per-item sha256
content hashes, synthetic pad ids spliced into the prefix-cache token
stream (so two prompts sharing a text+image prefix hit the same pages, and
two different images never do), full-prompt 3-D mrope positions with the
decode-extrapolation delta, and the token→visual-row index used to splice
ViT output rows into the step batch.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from gllm_tpu.models.config import ModelConfig
from gllm_tpu.ops.rope import get_mrope_input_positions

# Synthetic prefix-cache ids for visual spans: flag bit 1<<30 sits above
# every real vocab (reference model_runner.py:100-112); low 30 bits carry
# the item content hash.
_MM_PAD_ID_BASE = 1 << 30
_MM_PAD_ID_MASK = _MM_PAD_ID_BASE - 1


def mm_pad_id(content_hash: bytes) -> int:
    return _MM_PAD_ID_BASE | (int.from_bytes(content_hash[:4], "big")
                              & _MM_PAD_ID_MASK)


def content_hash(pixels: np.ndarray, grid_thw) -> bytes:
    """Per-item digest over pixel bytes + dtype/shape/grid (reference
    _hash_tensor_bytes / _build_item_content_hash)."""
    h = hashlib.sha256()
    arr = np.ascontiguousarray(pixels)
    h.update(str(arr.dtype).encode())
    h.update(repr(tuple(arr.shape)).encode())
    h.update(arr.tobytes())
    h.update(repr(tuple(int(v) for v in grid_thw)).encode())
    return h.digest()


@dataclasses.dataclass
class MMItem:
    modality: str                 # "image" | "video"
    # [n_patches, C*tps*ps*ps]; None for disagg items (the encoder process
    # owns the pixels; only grid + content hash reach the LM).
    pixels: Optional[np.ndarray]
    grid_thw: Tuple[int, int, int]
    hash: bytes


@dataclasses.dataclass
class MMState:
    """Per-sequence multimodal state, attached as ``Sequence.mm``."""
    items: List[MMItem]
    mrope_positions: np.ndarray          # [3, prompt_len] int32
    mrope_delta: int
    vis_index: np.ndarray                # [prompt_len] int32; -1 = text row
    num_vis_tokens: int
    hash_token_ids: List[int]            # prompt ids with pad-id splices
    # filled by the runner at first prefill (ViT output, prompt order):
    vis_embeds: Optional[np.ndarray] = None   # [num_vis_tokens, H]


def build_mm_state(token_ids: Sequence[int], cfg: ModelConfig,
                   pixel_values=None, image_grid_thw=None,
                   video_pixel_values=None, video_grid_thw=None,
                   second_per_grid_ts=None, grid_thws=None) -> MMState:
    """Build MMState from HF-processor outputs.

    ``pixel_values`` is the processor's concatenation over item rows;
    per-item slices are recovered from grid_thw (t*h*w rows each).
    ``grid_thws`` is the Kimi processor's name for the image grids.
    """
    if grid_thws is not None and image_grid_thw is None:
        image_grid_thw = grid_thws
    if cfg.mm_per_frame_video and video_grid_thw is not None:
        # Qwen3-VL: each temporal frame is its own vision span (HF
        # get_rope_index splits video_grid_thw the same way, and frames
        # are independent attention segments inside the ViT), so normalize
        # grids to t=1 per-frame items before slicing/hashing.
        grids = []
        for g in np.asarray(video_grid_thw):
            grids.extend([[1, int(g[1]), int(g[2])]] * int(g[0]))
        video_grid_thw = grids

    items: List[MMItem] = []

    def split_items(pixels, grids, modality):
        if pixels is None or grids is None:
            return
        pixels = np.asarray(pixels)
        off = 0
        for g in np.asarray(grids):
            n = int(g[0] * g[1] * g[2])
            chunk = pixels[off:off + n]
            off += n
            items.append(MMItem(modality, chunk,
                                (int(g[0]), int(g[1]), int(g[2])),
                                content_hash(chunk, g)))

    split_items(pixel_values, image_grid_thw, "image")
    split_items(video_pixel_values, video_grid_thw, "video")
    return finish_mm_state(token_ids, cfg, items, second_per_grid_ts)


def finish_mm_state(token_ids: Sequence[int], cfg: ModelConfig,
                    items: List[MMItem],
                    second_per_grid_ts=None) -> MMState:
    """The pixel-independent half: positions / vis-index / hash ids from an
    items list. Also the disagg entry point — items built from MmItemMeta
    (pixels=None, hash from the encoder) go through the same logic so the
    disagg stack is byte-identical to the monolith (reference oracle,
    docs/encoder_disaggregation_usage.md §11)."""
    if not cfg.mrope_section:
        # 1-D position models (Kimi K2.5 — reference kimi_k25.py uses the
        # DeepSeek backbone's plain positions): the mrope array is a
        # degenerate 3×arange that the forward path ignores.
        L = len(token_ids)
        pos1d = np.tile(np.arange(L, dtype=np.int64), (3, 1))
        positions, delta = pos1d, 0
    else:
        positions, delta = _mrope_positions(token_ids, cfg, items,
                                            second_per_grid_ts)
    return _index_and_hash(token_ids, cfg, items, positions, delta)


def _mrope_positions(token_ids, cfg, items, second_per_grid_ts):
    return get_mrope_input_positions(
        token_ids,
        [it.grid_thw for it in items if it.modality == "image"],
        [it.grid_thw for it in items if it.modality == "video"],
        image_token_id=cfg.image_token_id,
        video_token_id=cfg.video_token_id,
        spatial_merge_size=(cfg.vision_config or {}).get(
            "spatial_merge_size", 2),
        tokens_per_second=(cfg.vision_config or {}).get(
            "tokens_per_second", 1.0),
        second_per_grid_ts=second_per_grid_ts,
    )


def _index_and_hash(token_ids, cfg, items, positions, delta) -> MMState:
    ids = np.asarray(token_ids, np.int64)
    is_img = ids == cfg.image_token_id
    is_vid = ids == cfg.video_token_id
    is_vis = is_img | is_vid
    num_vis = int(is_vis.sum())
    # vis_embeds rows are concatenated in ITEMS order (images then videos,
    # matching embed order); the per-token index routes image placeholder
    # tokens into the image block and video tokens past it — prompt order
    # of modalities may interleave arbitrarily.
    n_img_tokens = int(is_img.sum())
    vis_index = np.full(len(ids), -1, np.int32)
    vis_index[is_img] = np.arange(int(is_img.sum()))
    vis_index[is_vid] = n_img_tokens + np.arange(int(is_vid.sum()))

    # Splice per-item pad ids over the placeholder runs, pairing runs (in
    # prompt order) with the next unused item(s) of the run's modality.
    # One run may cover SEVERAL consecutive items: per-frame-video models
    # lay the frames of one video back-to-back in a single span (and the
    # disagg skeleton expansion emits one contiguous span per raw item),
    # so each item consumes its own grid-worth of tokens within the run —
    # the same contract as get_mrope_input_positions, which also walks
    # back-to-back grids through one span.
    hash_ids = list(int(t) for t in token_ids)
    run_bounds = []
    prev = False
    for i, v in enumerate(is_vis):
        if v and not prev:
            run_bounds.append([i, i + 1])
        elif v:
            run_bounds[-1][1] = i + 1
        prev = bool(v)
    by_modality = {"image": [it for it in items if it.modality == "image"],
                   "video": [it for it in items if it.modality == "video"]}
    if len(run_bounds) == len(items):
        # 1:1 — each run is one whole item (token count per item is then
        # model-defined: e.g. Kimi's temporal pooling shrinks video runs
        # below the grid formula, which is fine because the run length IS
        # the item's token count here)
        for start, end in run_bounds:
            modality = "image" if is_img[start] else "video"
            item = by_modality[modality].pop(0)
            hash_ids[start:end] = [mm_pad_id(item.hash)] * (end - start)
    else:
        # fewer runs than items: back-to-back items share a span, so each
        # item's extent comes from its grid (exact for the per-frame
        # models whose normalization creates this layout)
        merge = (cfg.vision_config or {}).get("spatial_merge_size", 2)
        unit = merge * merge
        for start, end in run_bounds:
            modality = "image" if is_img[start] else "video"
            i = start
            while i < end:
                assert by_modality[modality], \
                    f"{modality} placeholder run at {start} has no item left"
                item = by_modality[modality].pop(0)
                t, h, w = item.grid_thw
                n = t * h * w // unit
                hash_ids[i:i + n] = [mm_pad_id(item.hash)] * n
                i += n
            assert i == end, (start, end, i)
    assert not by_modality["image"] and not by_modality["video"], \
        "items left over after all placeholder runs were filled"

    return MMState(items=items, mrope_positions=positions,
                   mrope_delta=delta, vis_index=vis_index,
                   num_vis_tokens=num_vis, hash_token_ids=hash_ids)
