"""MMLU-Pro-style multiple-choice accuracy eval against a running server
(reference benchmarks/evaluate_mmlu_pro.py).

Zero-egress environment: the dataset must be a LOCAL file
(``--data-path`` jsonl with fields: question, options (list), answer
(letter or index)). The prompting/extraction protocol mirrors the
reference: few-shot-free direct answering, "Answer:" extraction of the
first choice letter.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

LETTERS = "ABCDEFGHIJ"


def format_prompt(q):
    opts = "\n".join(f"{LETTERS[i]}. {o}"
                     for i, o in enumerate(q["options"]))
    return (f"Question: {q['question']}\nOptions:\n{opts}\n"
            "Answer with the option letter only.\nAnswer:")


def extract_choice(text):
    from mcq_common import extract_choice as _ec
    return _ec(text)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-path", required=True,
                    help="local jsonl: question/options/answer per line")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--limit", type=int, default=None)
    ap.add_argument("--concurrency", type=int, default=16)
    args = ap.parse_args()

    with open(args.data_path) as f:
        questions = [json.loads(line) for line in f if line.strip()]
    if args.limit:
        questions = questions[:args.limit]

    from eval_client import map_concurrent, post_json

    def ask(q):
        d = post_json(args.host, args.port, "/v1/chat/completions",
                      {"messages": [{"role": "user",
                                     "content": format_prompt(q)}],
                       "max_tokens": 8, "temperature": 0.0})
        return extract_choice(d["choices"][0]["message"]["content"] or "")

    answers = map_concurrent(ask, questions,
                             concurrency=args.concurrency,
                             label="mmlu_pro")
    correct = 0
    for q, got in zip(questions, answers):
        want = q["answer"]
        if isinstance(want, int):
            want = LETTERS[want]
        correct += int(got == str(want).strip().upper())
    total = len(questions)
    print(json.dumps({"metric": "mmlu_pro_accuracy",
                      "value": correct / max(1, total),
                      "n": total}))


if __name__ == "__main__":
    main()
