"""Measure pipeline-parallel microbatch overlap (VERDICT r02 weak #6).

The PP engine relies on async dispatch for pipelining: it keeps ``pp``
microbatches in flight (the role of the reference's explicit
pp_size-batches-running policy, scheduler.py:358-364) and XLA's
per-device execution queues overlap consecutive stage programs. This
script measures the two halves of that claim separately:

1. **Primitive asynchrony** — dispatch of a jitted program returns in
   ~0.1 ms while the work takes ~1 s, and ``jax.device_put`` of an
   in-flight array (the cross-stage hidden transfer) returns in <1 ms.
   If either blocked, pipelining would be dead on any backend.
2. **Engine dispatch timeline** — the pp=2 engine is run with the
   default depth (= pp) and instrumented ``step_async``/``collect``:
   for every collect we record how many OTHER microbatches were already
   fully dispatched (``inflight_at_collect``, 1.0 = perfect depth-2
   pipelining) and the mean launch latency vs the mean collect (device
   step) time. Launch ≪ step means the host never serializes stages.

Wall-clock speedup serial-vs-pipelined is also printed but is only
meaningful on real multi-chip hardware: the CPU mesh's virtual devices
share one host threadpool, so concurrent stage programs cannot run
faster even with perfect dispatch overlap (measured here: two-device
concurrent matmuls show 1.0x vs serial on CPU).

    # CPU mesh (default — a shell JAX_PLATFORMS is deliberately
    # overridden, see the pin below):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/pp_overlap.py [--trace-dir DIR]
    # real chips:
    PP_OVERLAP_ON_DEVICE=1 python benchmarks/pp_overlap.py
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Pin the CPU backend unless the caller explicitly opted onto real chips:
# the bench host's sitecustomize force-rewrites JAX_PLATFORMS to the TPU
# plugin at interpreter start, so a shell-level JAX_PLATFORMS=cpu does
# NOT survive — it must be reasserted here, before jax is imported.
if os.environ.get("PP_OVERLAP_ON_DEVICE") != "1":
    if os.environ.get("JAX_PLATFORMS") not in (None, "", "cpu"):
        print("pp_overlap: overriding JAX_PLATFORMS="
              f"{os.environ['JAX_PLATFORMS']!r} with 'cpu' — set "
              "PP_OVERLAP_ON_DEVICE=1 to measure on real chips",
              file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"


def primitive_asynchrony():
    """Dispatch latency and in-flight device_put latency vs work time."""
    import jax
    import jax.numpy as jnp
    d0, d1 = jax.devices()[0], jax.devices()[1]

    @jax.jit
    def f(x):
        for _ in range(20):
            x = x @ x
        return x

    x0 = jax.device_put(jnp.ones((1200, 1200)), d0)
    jax.block_until_ready(f(x0))                      # compile
    t0 = time.monotonic()
    r = f(x0)
    t_dispatch = time.monotonic() - t0
    y = jax.device_put(r, d1)                         # in-flight transfer
    t_put = time.monotonic() - t0 - t_dispatch
    jax.block_until_ready(y)
    t_work = time.monotonic() - t0
    return {"dispatch_ms": round(t_dispatch * 1e3, 2),
            "inflight_put_ms": round(t_put * 1e3, 2),
            "work_ms": round(t_work * 1e3, 1)}


def build_llm(depth):
    from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                                 SchedulerConfig)
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.models.config import ModelConfig

    mcfg = ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=1024, hidden_size=256,
        num_layers=4, num_heads=4, num_kv_heads=4, head_dim=64,
        intermediate_size=768, max_position=512)
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=128,
        max_num_seqs=32, pp_pipeline_depth=depth,
        scheduler=SchedulerConfig(schedule_method="token_throttling",
                                  max_prefill_tokens=128,
                                  min_prefill_tokens=32,
                                  max_decode_seqs=8),
        cache=CacheConfig(page_size=16, num_pages=256),
        parallel=ParallelConfig(pp=2, tp=1))
    return LLM(config=cfg, model_cfg=mcfg)


def run(llm, n_seqs=16, max_tokens=24):
    from gllm_tpu.sampling_params import SamplingParams
    prompts = [[(7 * i + j) % 1000 for j in range(8)] for i in range(n_seqs)]
    t0 = time.monotonic()
    outs = llm.generate(prompt_token_ids=prompts,
                        sampling_params=SamplingParams(
                            temperature=0.0, max_tokens=max_tokens,
                            ignore_eos=True))
    dt = time.monotonic() - t0
    assert all(len(o.output_token_ids) == max_tokens for o in outs)
    return dt


def instrument(llm):
    """Wrap the runner's launch/collect with a host-side event log."""
    runner = llm.runner
    state = {"inflight": 0, "launch_ms": [], "collect_ms": [],
             "build_ms": [], "inflight_at_collect": []}
    orig_launch, orig_collect = runner.step_async, runner.collect
    orig_build = runner.builder.build

    def build(*a, **kw):
        t0 = time.monotonic()
        out = orig_build(*a, **kw)
        state["build_ms"].append((time.monotonic() - t0) * 1e3)
        return out

    runner.builder.build = build

    def step_async(batch):
        t0 = time.monotonic()
        h = orig_launch(batch)
        state["launch_ms"].append((time.monotonic() - t0) * 1e3)
        state["inflight"] += 1
        return h

    def collect(handle):
        state["inflight_at_collect"].append(state["inflight"] - 1)
        t0 = time.monotonic()
        out = orig_collect(handle)
        state["collect_ms"].append((time.monotonic() - t0) * 1e3)
        state["inflight"] -= 1
        return out

    runner.step_async, runner.collect = step_async, collect
    return state


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", default=None,
                    help="write a jax.profiler trace of the pipelined run")
    args = ap.parse_args()
    if os.environ.get("PP_OVERLAP_ON_DEVICE") != "1":
        # belt and braces with the env pin above: the axon sitecustomize
        # also pre-sets the jax_platforms config default
        import jax
        jax.config.update("jax_platforms", "cpu")

    prims = primitive_asynchrony()
    print(f"primitives: {prims}", file=sys.stderr, flush=True)

    wall = {}
    timeline = None
    for label, depth in (("serial", 1), ("pipelined", None)):
        llm = build_llm(depth)
        # warmup = the EXACT measured workload, so no bucket compiles
        # pollute the measured pass (a single mid-run compile would
        # dominate the launch-latency mean)
        run(llm)
        if label == "pipelined":
            timeline = instrument(llm)
        if label == "pipelined" and args.trace_dir:
            import jax
            with jax.profiler.trace(args.trace_dir):
                wall[label] = run(llm)
        else:
            wall[label] = run(llm)
        print(f"{label:10s} {wall[label]:.3f}s", file=sys.stderr,
              flush=True)
        del llm

    mean = lambda xs: sum(xs) / max(1, len(xs))
    # decode-phase collects (prefill bursts excluded) are the steady state
    ac = timeline["inflight_at_collect"]
    steady = ac[len(ac) // 4:]
    print(json.dumps({
        "primitive": prims,
        "t_serial_s": round(wall["serial"], 3),
        "t_pipelined_s": round(wall["pipelined"], 3),
        "cpu_wall_note": "virtual CPU devices share one host threadpool; "
                         "wall-clock gain only appears on real chips",
        "build_ms_mean": round(mean(timeline["build_ms"]), 2),
        "launch_ms_mean": round(mean(timeline["launch_ms"]), 2),
        "collect_ms_mean": round(mean(timeline["collect_ms"]), 2),
        "inflight_at_collect_mean": round(mean(steady), 3),
        # the engine-level property provable on CPU: while one microbatch
        # is being collected another is already fully dispatched (host
        # launch latencies are NOT comparable to chip numbers here — CPU
        # device programs share cores with the host thread)
        "dispatch_pipelined": mean(steady) > 0.8,
    }))


if __name__ == "__main__":
    main()
