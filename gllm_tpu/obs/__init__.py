"""Dependency-free observability layer (metrics, step traces, spans).

Three pillars, all pure-host bookkeeping (no jax import, no device work,
no effect on jit cache keys):

- ``gllm_tpu.obs.metrics``: a Prometheus-style registry (Counter / Gauge /
  Histogram with fixed buckets, thread-safe, text-exposition renderer)
  served by the api_server's ``GET /metrics``.
- ``gllm_tpu.obs.steptrace``: a ring buffer of per-step records (kind,
  batch size, token counts, wall ms, and the engine-loop phase/device
  attribution fields) dumped by ``GET /steptrace`` and summarized into
  bench.py's metrics snapshot. ``python -m gllm_tpu.obs.dump
  trace.jsonl`` pretty-prints a saved trace.
- ``gllm_tpu.obs.spans``: the performance-attribution layer — per-request
  span trees, the step FLOPs model behind ``gllm_step_mfu``, and the
  Chrome trace-event converter behind ``GET /trace`` and ``obs.dump
  --format chrome`` (docs/observability.md#tracing--attribution).

Every round-5 finding (unfused decode steps at 8x the fused latency, the
sampled-path sort, the tuning-table regression) had to be excavated from
ad-hoc stderr logs; this layer makes the same questions one HTTP GET or
one JSON blob.
"""

from gllm_tpu.obs import metrics, spans, steptrace  # noqa: F401
