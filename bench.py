#!/usr/bin/env python
"""Headline benchmark: synthetic-ShareGPT offline throughput.

Mirrors the reference's measurement harness
(/root/reference/examples/batch_inference.py:56-74 — offline ShareGPT
reqs/s + output tok/s) with a synthetic, zero-egress workload: a
Llama-3.2-1B-shaped dummy-weight model served by the full engine
(continuous batching + chunked prefill + paged KV) on one chip.

Prints exactly ONE JSON line to stdout:
  {"metric": "sharegpt_output_tok_s_per_chip", "value": N, "unit": "tok/s",
   "vs_baseline": N / 2000.0}

vs_baseline denominator: BASELINE.json's flagship target (2000 output tok/s
for Llama-3-70B PP=8 on v5e-8 — i.e. ~250 tok/s/chip × 8; a 1B model on one
chip should beat it by a wide margin; it is the round-over-round yardstick).

Robustness: the default invocation is a supervisor that runs the actual
benchmark in a child process under a hard deadline, retries once on
backend-init failure/hang (round 1 died with "Unable to initialize backend
'axon'" and produced no number), and on unrecoverable failure still prints
one parseable JSON line with an "error" field.

Usage: python bench.py            # real chip (axon/tpu)
       python bench.py --tiny     # CPU smoke (small model, small workload)
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import subprocess
import sys
import time

METRIC = "sharegpt_output_tok_s_per_chip"


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def supervise(args, argv):
    """Run the real benchmark in a child process; retry once; always print
    one JSON line."""
    attempts = 2
    # First attempt gets the full budget (TPU backend init via the tunnel
    # can take minutes); the retry gets the remainder.
    deadline = time.monotonic() + (900 if not args.tiny else 420)
    last_tail = ""
    for attempt in range(1, attempts + 1):
        # per-attempt cap so a mid-run hang (wedged tunnel) still leaves
        # any later attempt a real budget
        budget = max(60, min(deadline - time.monotonic(), 620))
        log(f"[bench supervisor] attempt {attempt}/{attempts}, "
            f"budget {budget:.0f}s")
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--inner"]
                + argv,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, timeout=budget)
            tail = proc.stdout[-8000:]
            sys.stderr.write(tail)
            sys.stderr.flush()
            if proc.returncode == 0:
                # The inner run prints the JSON line last.
                for line in reversed(proc.stdout.strip().splitlines()):
                    line = line.strip()
                    if line.startswith("{"):
                        try:
                            parsed = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if parsed.get("metric") == METRIC:
                            print(line)
                            return 0
            last_tail = tail[-1500:]
        except subprocess.TimeoutExpired as e:
            out = (e.stdout or b"")
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            last_tail = (out[-1500:] + f"\n[timeout after {budget:.0f}s]")
            log(f"[bench supervisor] attempt {attempt} timed out")
        if time.monotonic() >= deadline - 60:
            break
    print(json.dumps({
        "metric": METRIC, "value": 0.0, "unit": "tok/s",
        "vs_baseline": 0.0,
        "error": f"benchmark failed after {attempts} attempts: "
                 + last_tail[-900:],
    }))
    return 0


def build_workload(rng, n_requests, max_model_len, tiny=False):
    """Synthetic ShareGPT-like length distribution."""
    from gllm_tpu.sampling_params import SamplingParams
    prompts, params = [], []
    for _ in range(n_requests):
        if tiny:
            p_len = int(rng.integers(8, 64))
            o_len = int(rng.integers(8, 32))
        else:
            p_len = int(min(max(rng.lognormal(5.2, 0.8), 16), 1024))
            o_len = int(min(max(rng.lognormal(4.8, 0.7), 16), 512))
        p_len = min(p_len, max_model_len - o_len - 1)
        prompts.append(rng.integers(1, 30000, size=p_len).tolist())
        params.append(SamplingParams(temperature=0.0, max_tokens=o_len,
                                     ignore_eos=True))
    return prompts, params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke test (small model/workload)")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--inner", action="store_true",
                    help="(internal) run the measurement directly; without"
                         " this flag a supervisor child-process wrapper"
                         " with deadline+retry is used")
    args = ap.parse_args()

    if not args.inner:
        argv = [a for a in sys.argv[1:] if a != "--inner"]
        sys.exit(supervise(args, argv))

    # Stall forensics: dump all thread stacks to stderr every 5 minutes so
    # a wedged run (tunnel stall, compile hang, deadlock) leaves evidence.
    import faulthandler
    faulthandler.dump_traceback_later(300, repeat=True, file=sys.stderr)

    if args.tiny:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(os.path.dirname(__file__) or ".",
                                       ".jax_cache"))
    import numpy as np
    import jax
    if args.tiny:
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ["JAX_COMPILATION_CACHE_DIR"])
    except Exception:
        pass
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)

    from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.models.config import ModelConfig

    if args.tiny:
        model_cfg = ModelConfig(
            architecture="LlamaForCausalLM", vocab_size=2048,
            hidden_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=32, intermediate_size=256, max_position=512)
        engine_cfg = EngineConfig(
            load_format="dummy", dtype="float32", max_model_len=512,
            max_num_seqs=32,
            scheduler=SchedulerConfig(max_prefill_tokens=128,
                                      max_decode_seqs=16),
            cache=CacheConfig(page_size=4, num_pages=512))
        n_requests = args.requests or 8
    else:
        # Llama-3.2-1B shape (BASELINE config 1), dummy weights.
        model_cfg = ModelConfig(
            architecture="LlamaForCausalLM", vocab_size=128256,
            hidden_size=2048, num_layers=16, num_heads=32, num_kv_heads=8,
            head_dim=64, intermediate_size=8192, max_position=4096,
            rope_theta=500000.0, tie_word_embeddings=True)
        engine_cfg = EngineConfig(
            load_format="dummy", dtype="bfloat16", max_model_len=2048,
            max_num_seqs=256, overlap_scheduling=True, overlap_depth=4,
            multi_step_decode=8,
            scheduler=SchedulerConfig(max_prefill_tokens=1024,
                                      max_decode_seqs=256),
            # explicit pool (4 GB KV): the axon-attached chip advertises
            # no memory_stats and over-allocating hangs device init
            cache=CacheConfig(page_size=16, num_pages=8192))
        n_requests = args.requests or 160

    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    t0 = time.monotonic()
    llm = LLM(config=engine_cfg, model_cfg=model_cfg)
    log(f"engine up in {time.monotonic() - t0:.1f}s "
        f"({llm.runner.num_pages} KV pages)")

    rng = np.random.default_rng(args.seed)
    prompts, params = build_workload(rng, n_requests,
                                     engine_cfg.max_model_len,
                                     tiny=args.tiny)
    total_out = sum(p.max_tokens for p in params)
    total_in = sum(len(p) for p in prompts)
    log(f"workload: {n_requests} reqs, {total_in} prompt tokens, "
        f"{total_out} output tokens")

    # Warmup pass: same workload → compiles every bucket the measured pass
    # will hit (the reference warms its CUDA graphs the same way).
    t0 = time.monotonic()
    llm.generate(prompt_token_ids=prompts, sampling_params=params)
    log(f"warmup pass: {time.monotonic() - t0:.1f}s")

    t0 = time.monotonic()
    outs = llm.generate(prompt_token_ids=prompts, sampling_params=params)
    dt = time.monotonic() - t0

    out_tokens = sum(o.num_output_tokens for o in outs)
    assert out_tokens == total_out, (out_tokens, total_out)
    value = out_tokens / dt
    log(f"measured pass: {dt:.2f}s → {value:.1f} output tok/s "
        f"({n_requests / dt:.2f} req/s)")
    print(json.dumps({
        "metric": METRIC,
        "value": round(value, 2),
        "unit": "tok/s",
        "vs_baseline": round(value / 2000.0, 4),
    }))


if __name__ == "__main__":
    main()
