"""Paged KV-cache bookkeeping + hash-chain prefix cache.

Host-side page accounting for the HBM KV arrays owned by the ModelRunner. This
is the TPU-native analogue of the reference MemoryManager / PrefixMemoryManager
(/root/reference/gllm/memory_manager.py):

- pages are fixed-size slabs of KV slots; a sequence's ``page_table`` lists its
  page ids in order; flat KV slot = page_id * page_size + offset.
- page id 0 is reserved as the *dummy page*: padded batch rows and padded
  tokens write there (reference memory_manager.py:522 uses a dummy page the
  same way for CUDA-graph padding).
- prefix cache (reference memory_manager.py:858-1272): a chained per-page hash
  (O(page) to extend, :898-917) keys full pages for reuse; pages are
  ref-counted (:1250-1262); a cached page *survives refcount 0* and remains
  reusable until the allocator re-mints it for other content (:1254-1262); an
  8-token canary guards against hash collisions (:920-935).
- registration of freshly computed pages is decoupled from allocation and
  driven by the scheduler after outputs land (:1055-1079) so in-flight
  (placeholder) tokens never poison cache keys.

Differences from the reference are deliberate: there is no per-GPU process, so
one manager serves all local devices of a replica; KV sizing from live HBM
telemetry happens in the runner, which passes ``num_pages`` here.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from gllm_tpu.id_allocator import IDAllocator
from gllm_tpu.obs import metrics as obs
from gllm_tpu.obs.steptrace import TRACE
from gllm_tpu.sequence import Sequence
from gllm_tpu.utils import cdiv

# Prefix-cache metrics (docs/observability.md): lifetime token counters —
# rate(hit)/rate(query) gives the windowed hit rate in any scraper; the
# scheduler's gllm_prefix_cache_hit_rate gauge mirrors the lifetime ratio.
_M_PFX_QUERY = obs.counter("gllm_prefix_cache_query_tokens_total",
                           "prompt tokens probed against the prefix cache")
_M_PFX_HIT = obs.counter("gllm_prefix_cache_hit_tokens_total",
                         "prompt tokens served from cached KV pages")

# Tokens stored per cached page to verify against hash collisions
# (reference memory_manager.py:920-935).
_CANARY_TOKENS = 8

# Chain-parent map bound (digest -> predecessor digest, LRU): the lower
# prefix tiers (gllm_tpu/kvstore) use the edge for read-ahead; a capped
# map loses only the oldest edges (a lost edge costs a prefetch, never
# correctness).
_PARENT_CAP = 1 << 16


def _chain_hash(prev: bytes, token_ids: List[int], extra_key: bytes = b"") -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(prev)
    h.update(extra_key)
    h.update(b"".join(t.to_bytes(4, "little", signed=True) for t in token_ids))
    return h.digest()


def prefix_digests(cache_token_ids, prompt_len: int, page_size: int,
                   extra_key: bytes = b"") -> List[Tuple[bytes, list]]:
    """Chained page digests over the cacheable prompt prefix — only whole
    pages, leaving >= 1 token to compute (the match_prefix guarantee).
    Replica-independent: cache-aware DP routing computes this ONCE and
    probes every replica's maps with it."""
    out: List[Tuple[bytes, list]] = []
    digest = b"root"
    for i in range((prompt_len - 1) // page_size):
        s = i * page_size
        tokens = cache_token_ids[s:s + page_size]
        digest = _chain_hash(digest, tokens, extra_key)
        out.append((digest, tokens))
    return out


class MemoryManager:
    """Plain paged allocator (no prefix reuse).

    For hybrid (GDN) models it additionally owns the SSM slot allocators
    (reference SSMSegment, memory_manager.py:87-255): one *working* slot
    per live request plus an optional *snapshot* range for cached-prefix
    state. The device arrays live with the runner; this class only hands
    out slot ids and records copy/zero intents the runner applies before
    its next step (single-controller, so FIFO intent order is exact).
    Slot 0 is the padding dummy in both ranges.
    """

    def __init__(self, num_pages: int, page_size: int,
                 ssm_working_slots: int = 0, ssm_snapshot_slots: int = 0):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (one is the dummy page)")
        self.page_size = page_size
        self.num_pages = num_pages
        self.dummy_page = 0
        # Page 0 reserved for padding writes.
        self.allocator = IDAllocator(num_pages - 1, start=1)
        self.ref_count: Dict[int, int] = {}
        # Host-RAM KV tier (gllm_tpu/kvswap.KVSwapManager) — attached by
        # the engine when a host pool is configured; None keeps every
        # code path byte-for-byte the pre-offload behavior.
        self.swap = None
        # int8 KV cache (kv_cache_dtype=int8): minted pages queue a
        # device-side SCALE RESET (drained by the runner before the next
        # step, ordered between the host tier's gathers and scatters) so
        # a recycled page quantizes like a fresh one — quantization
        # never depends on page-reuse history, and the running absmax
        # cannot ratchet across tenants. Off (flag False) this list
        # stays empty and no reset program ever dispatches.
        self.track_scale_resets = False
        self.scale_resets: List[int] = []

        self.ssm_working_slots = ssm_working_slots
        self.ssm_snapshot_slots = ssm_snapshot_slots
        if ssm_working_slots:
            self.ssm_alloc: Optional[IDAllocator] = IDAllocator(
                ssm_working_slots, start=1)
            self.ssm_snap_alloc: Optional[IDAllocator] = (
                IDAllocator(ssm_snapshot_slots,
                            start=1 + ssm_working_slots)
                if ssm_snapshot_slots else None)
        else:
            self.ssm_alloc = None
            self.ssm_snap_alloc = None
        # ("snapshot", work, snap) | ("zero", slot, 0) | ("restore", snap,
        # work) — drained by the runner, applied snapshot→zero→restore.
        self.ssm_intents: List[Tuple[str, int, int]] = []
        self._snap_free_pending: List[int] = []

    # ---- SSM slots (hybrid models) ----------------------------------------

    @property
    def use_ssm(self) -> bool:
        return self.ssm_alloc is not None

    def can_admit_seq(self) -> bool:
        return self.ssm_alloc is None or self.ssm_alloc.num_free > 0

    def prepare_seq(self, seq: Sequence) -> None:
        """Allocate per-seq auxiliary state at admission (waiting→running):
        a fresh (zeroed-on-free) SSM working slot, plus the prefix-cache
        state restore recorded by match_prefix."""
        if self.ssm_alloc is None:
            return
        if getattr(seq, "ssm_slot", None) is None:
            seq.ssm_slot = self.ssm_alloc.allocate()
        snap = getattr(seq, "_ssm_restore_snap", None)
        if snap is not None:
            self.ssm_intents.append(("restore", snap, seq.ssm_slot))
            seq._ssm_restore_snap = None

    def _free_ssm(self, seq: Sequence) -> None:
        slot = getattr(seq, "ssm_slot", None)
        if slot is not None:
            # Drop pending restores INTO this slot (e.g. a spec-decode
            # rollback for a seq preempted before the drain): the slot may
            # be reallocated before the intents apply, and restores run
            # AFTER zeros — a stale one would clobber the new tenant.
            self.ssm_intents = [t for t in self.ssm_intents
                                if not (t[0] == "restore"
                                        and t[2] == slot)]
            self.ssm_intents.append(("zero", slot, 0))
            self.ssm_alloc.free(slot)
            seq.ssm_slot = None

    def free_snap_after_drain(self, snap: int) -> None:
        """Return a snapshot slot to the pool only once the currently
        pending intents have been drained. A pending ``restore`` may still
        read the slot; an immediate free could let a NEW ``snapshot``
        claim it in the same drain batch — and snapshots apply BEFORE
        restores, so the restore would read the new tenant's state."""
        self._snap_free_pending.append(snap)

    def drain_ssm_intents(self) -> List[Tuple[str, int, int]]:
        out, self.ssm_intents = self.ssm_intents, []
        pend, self._snap_free_pending = self._snap_free_pending, []
        for snap in pend:
            self.ssm_snap_alloc.free(snap)
        return out

    # ---- stats ------------------------------------------------------------

    @property
    def num_free_pages(self) -> int:
        return self.allocator.num_free

    @property
    def free_ratio(self) -> float:
        return self.allocator.num_free / self.allocator.num_total

    # ---- allocation -------------------------------------------------------

    def pages_needed(self, seq: Sequence, num_new_tokens: int) -> int:
        return cdiv(seq.num_computed_tokens + num_new_tokens,
                    self.page_size) - len(seq.page_table)

    def can_allocate(self, num_pages: int) -> bool:
        return self.num_free_pages >= num_pages

    def _mint_page(self) -> int:
        page = self.allocator.allocate()
        if self.track_scale_resets:
            self.scale_resets.append(page)
        return page

    def drain_scale_resets(self) -> List[int]:
        out, self.scale_resets = self.scale_resets, []
        return out

    def allocate_seq_pages(self, seq: Sequence, num_new_tokens: int) -> None:
        """Extend ``seq.page_table`` to cover computed+num_new_tokens tokens.

        Caller must have checked ``can_allocate(pages_needed(...))``.
        """
        for _ in range(self.pages_needed(seq, num_new_tokens)):
            page = self._mint_page()
            self.ref_count[page] = 1
            seq.page_table.append(page)

    def match_prefix(self, seq: Sequence) -> int:
        """Prefix-cache hook; no-op without prefix caching."""
        return 0

    def peek_prefix(self, cache_token_ids, prompt_len: int) -> int:
        """Read-only prefix-match estimate; 0 without prefix caching."""
        return 0

    def peek_digests(self, digests) -> int:
        """Read-only prefix-match estimate; 0 without prefix caching."""
        return 0

    def register_computed_pages(self, seq: Sequence) -> None:
        """Prefix-cache hook; no-op without prefix caching."""

    def free_seq(self, seq: Sequence) -> None:
        for page in seq.page_table:
            self._release_page(page)
        seq.page_table = []
        seq._pt_np = None      # see Sequence.preempt: shrink ⇒ drop cache
        if self.swap is not None and seq.swap_host_pages:
            # SWAPPED seq freed without resuming (abort / shutdown):
            # return its host-tier pages too
            self.swap.release_seq(seq)
        self._free_ssm(seq)

    def _release_page(self, page: int) -> None:
        self.ref_count[page] -= 1
        if self.ref_count[page] == 0:
            del self.ref_count[page]
            self.allocator.free(page)


class PrefixMemoryManager(MemoryManager):
    """Paged allocator with page-granular hash-keyed KV reuse."""

    def __init__(self, num_pages: int, page_size: int, **ssm_kwargs):
        super().__init__(num_pages, page_size, **ssm_kwargs)
        # hash digest -> page id (only fully computed pages).
        self.hash_to_page: Dict[bytes, int] = {}
        # page id -> (hash digest, canary token ids)
        self.page_meta: Dict[int, Tuple[bytes, Tuple[int, ...]]] = {}
        # per-seq chained hash of the last registered page, for O(page)
        # extension (reference memory_manager.py:898-917 caches the chain on
        # the sequence; we key it by seq id here).
        self._seq_chain: Dict[int, Tuple[int, bytes]] = {}  # seq_id -> (num_pages_hashed, digest)
        # hybrid: page id → SSM snapshot slot holding the state at that
        # page's boundary (reference page2ssm_snapshot; entries here are
        # always valid — slots are allocated at capture time, not
        # pre-reserved).
        self.page2snap: Dict[int, int] = {}
        self.hit_tokens = 0
        self.query_tokens = 0
        # digest -> chain-predecessor digest (None for a chain head),
        # LRU-capped; consumed by the host spill so demoted pages carry
        # their read-ahead edge down the tier stack.
        self._digest_parent: "OrderedDict[bytes, Optional[bytes]]" = \
            OrderedDict()

    def _note_parent(self, digest: bytes,
                     parent: Optional[bytes]) -> None:
        self._digest_parent[digest] = parent
        self._digest_parent.move_to_end(digest)
        while len(self._digest_parent) > _PARENT_CAP:
            self._digest_parent.popitem(last=False)

    # A page in the free list may still carry cache metadata; minting it for
    # new content must drop the stale key (reference :1254-1262).
    def _mint_page(self) -> int:
        page = super()._mint_page()   # keeps the int8 scale-reset queue
        meta = self.page_meta.pop(page, None)
        if meta is not None:
            digest, canary = meta
            if self.hash_to_page.get(digest) == page:
                del self.hash_to_page[digest]
                if self.swap is not None:
                    # this was the canonical copy of its content — spill
                    # it to the host tier instead of losing it (eviction
                    # becomes a transfer, not a future re-prefill)
                    self.swap.spill_prefix(
                        page, digest, canary,
                        parent=self._digest_parent.get(digest))
        self._release_snapshot_for(page)
        return page

    def _restore_from_host(self, digest: bytes, tokens) -> Optional[int]:
        """Host-tier prefix probe for match_prefix: on a (canary-verified)
        hit, mint a fresh device page, queue the host->device restore,
        and re-register the digest device-side. None = miss / no device
        page to restore into."""
        if self.swap is None:
            return None
        host_page = self.swap.match_host_prefix(digest, tokens)
        if host_page is None:
            return None
        if not self.can_allocate(1):
            self.swap.release_probe_pin(host_page)
            return None
        # the probe pin guards host_page across this mint: the mint's
        # own spill may allocate (and evict) in the host pool, and the
        # hit must not be its victim
        page = self._mint_page()
        self.swap.restore_prefix(host_page, page)   # takes its own pin
        self.swap.release_probe_pin(host_page)
        self.hash_to_page[digest] = page
        self.page_meta[page] = (digest, tuple(tokens[:_CANARY_TOKENS]))
        return page

    def _release_snapshot_for(self, page: int) -> None:
        """Drop the SSM snapshot of a page's previous tenant (reference
        memory_manager.py _release_snapshot_for)."""
        snap = self.page2snap.pop(page, None)
        if snap is not None:
            self.ssm_snap_alloc.free(snap)

    def _page_tokens(self, seq: Sequence, page_idx: int) -> List[int]:
        s = page_idx * self.page_size
        # cache_token_ids splices multimodal content-hash pad ids over
        # visual spans (Sequence.cache_token_ids).
        return seq.cache_token_ids[s:s + self.page_size]

    def _probe_page(self, digest: bytes, tokens) -> Optional[int]:
        """Cached page id for this chained digest, or None (missing /
        canary mismatch = hash collision). Shared by the claiming walk
        (match_prefix) and the read-only routing peek so the two can
        never disagree on what counts as a hit."""
        page = self.hash_to_page.get(digest)
        if page is None:
            return None
        _, canary = self.page_meta[page]
        if tuple(tokens[:_CANARY_TOKENS]) != canary:
            return None
        return page

    def peek_digests(self, digests) -> int:
        """Read-only estimate of the tokens ``match_prefix`` would claim,
        given ``prefix_digests(...)`` output — no refcounts/claims. Used
        by cache-aware DP routing (the frontend hashes the prompt ONCE
        and probes every replica); the hybrid SSM-snapshot rollback
        refinement is deliberately skipped (this is a routing heuristic,
        not a reservation)."""
        matched = 0
        for digest, tokens in digests:
            if self._probe_page(digest, tokens) is None:
                break
            matched += 1
        return matched * self.page_size

    def peek_prefix(self, cache_token_ids, prompt_len: int,
                    extra_key: bytes = b"") -> int:
        return self.peek_digests(prefix_digests(
            cache_token_ids, prompt_len, self.page_size, extra_key))

    def match_prefix(self, seq: Sequence, extra_key: bytes = b"") -> int:
        """Claim cached pages covering the longest matching prompt prefix.

        Returns the number of cached tokens (always < prompt_len so at least
        one token is computed to produce logits — same guarantee the reference
        keeps). Claimed pages get ref_count++ and enter seq.page_table.
        """
        assert seq.num_computed_tokens == 0 and not seq.page_table
        self.query_tokens += seq.prompt_len
        _M_PFX_QUERY.inc(seq.prompt_len)
        matched_digest = b"root"
        matched = 0
        digests: List[bytes] = []
        page_tiers: List[str] = []   # which tier served each claimed page
        for digest, tokens in prefix_digests(
                seq.cache_token_ids, seq.prompt_len, self.page_size,
                extra_key):
            self._note_parent(digest,
                              matched_digest if digests else None)
            page = self._probe_page(digest, tokens)
            tier = "hbm" if page is not None else None
            if page is None:
                # HBM miss → lower tiers (gllm_tpu/kvswap + kvstore,
                # probe order host → disk → peer): a hit mints a fresh
                # device page and queues the restore copy, which the
                # runner drains before the step that reads it.
                page = self._restore_from_host(digest, tokens)
                if page is not None:
                    tier = getattr(self.swap, "last_hit_tier",
                                   None) or "host"
            if page is None:
                break
            if self.allocator.is_free(page):
                self.allocator.allocate_id(page)
            self.ref_count[page] = self.ref_count.get(page, 0) + 1
            seq.page_table.append(page)
            matched += 1
            matched_digest = digest
            digests.append(digest)
            page_tiers.append(tier)
        if self.use_ssm and matched:
            # Hybrid: a KV hit is only usable up to the last page whose SSM
            # snapshot exists — roll the claim back to that boundary
            # (reference _rollback_to_last_ssm_hit). Without any snapshot,
            # the whole hit is dropped: replaying from token 0 with a
            # claimed-but-stateless prefix would corrupt the recurrence.
            keep = matched
            while keep > 0 and seq.page_table[keep - 1] not in self.page2snap:
                keep -= 1
            for page in seq.page_table[keep:]:
                self._release_page(page)
            del seq.page_table[keep:]
            seq._pt_np = None  # see Sequence.preempt: shrink ⇒ drop cache
            if keep:
                matched_digest = digests[keep - 1]
                seq._ssm_restore_snap = self.page2snap[
                    seq.page_table[keep - 1]]
            matched = keep
        seq.num_computed_tokens = matched * self.page_size
        seq.num_cached_tokens = seq.num_computed_tokens
        if matched:
            self._seq_chain[seq.seq_id] = (matched, matched_digest)
        self.hit_tokens += seq.num_computed_tokens
        _M_PFX_HIT.inc(seq.num_computed_tokens)
        # Per-tier attribution on the steptrace ring: one event per
        # admission probe; steptrace.summarize() reduces a window to a
        # per-tier prefix hit rate (docs/observability.md). The SSM
        # rollback above trimmed the claim, so count only kept pages.
        pages: Dict[str, int] = {}
        for t in page_tiers[:matched]:
            pages[t] = pages.get(t, 0) + 1
        TRACE.record("prefix", query_tokens=seq.prompt_len,
                     hit_tokens=seq.num_computed_tokens, pages=pages)
        return seq.num_computed_tokens

    def register_computed_pages(self, seq: Sequence, extra_key: bytes = b"") -> None:
        """Register hashes for fully computed pages of ``seq``.

        Called by the scheduler *after* outputs for a step landed, so only real
        (non-placeholder) tokens are ever hashed (reference :1055-1079).

        Hybrid: when the just-computed range ends exactly at a page
        boundary (and the seq has no chained step in flight that would have
        advanced the device state past it), the working SSM state IS the
        state at that boundary — capture it into a snapshot slot tied to
        the page (reference _maybe_snapshot_state, qwen3_5.py:307-360).
        """
        full_pages = seq.num_computed_tokens // self.page_size
        n_hashed, digest = self._seq_chain.get(seq.seq_id, (0, b"root"))
        for i in range(n_hashed, min(full_pages, len(seq.page_table))):
            tokens = self._page_tokens(seq, i)
            parent = digest if digest != b"root" else None
            digest = _chain_hash(digest, tokens, extra_key)
            self._note_parent(digest, parent)
            page = seq.page_table[i]
            existing = self.hash_to_page.get(digest)
            if existing is None:
                self.hash_to_page[digest] = page
                self.page_meta[page] = (digest, tuple(tokens[:_CANARY_TOKENS]))
                if (self.ssm_snap_alloc is not None
                        and (i + 1) * self.page_size
                        == seq.num_computed_tokens
                        and not seq.num_in_flight
                        and getattr(seq, "ssm_slot", None) is not None
                        and page not in self.page2snap
                        and self.ssm_snap_alloc.num_free > 0):
                    snap = self.ssm_snap_alloc.allocate()
                    self.page2snap[page] = snap
                    self.ssm_intents.append(("snapshot", seq.ssm_slot,
                                             snap))
            n_hashed = i + 1
        self._seq_chain[seq.seq_id] = (n_hashed, digest)

    def free_seq(self, seq: Sequence) -> None:
        super().free_seq(seq)
        self._seq_chain.pop(seq.seq_id, None)

    @property
    def cache_hit_rate(self) -> float:
        return self.hit_tokens / self.query_tokens if self.query_tokens else 0.0


def make_memory_manager(num_pages: int, page_size: int,
                        enable_prefix_caching: bool,
                        ssm_working_slots: int = 0,
                        ssm_snapshot_slots: int = 0) -> MemoryManager:
    cls = PrefixMemoryManager if enable_prefix_caching else MemoryManager
    return cls(num_pages, page_size, ssm_working_slots=ssm_working_slots,
               ssm_snapshot_slots=ssm_snapshot_slots)
