"""gllm-tpu: a TPU-native distributed LLM inference/serving engine.

Built from scratch on JAX/XLA/Pallas with the capability surface of
gty111/gLLM (continuous batching, chunked prefill, paged KV cache with prefix
caching, token-throttling pipeline scheduling, TP/PP/EP/DP parallelism, an
OpenAI-compatible server) — re-architected for TPU: single-controller SPMD
over a device mesh, jit-compiled bucketed step functions instead of CUDA
graphs, Pallas ragged paged attention, and XLA ICI collectives instead of
NCCL.
"""

from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                             SchedulerConfig)
from gllm_tpu.sampling_params import SamplingParams

__version__ = "0.1.0"

__all__ = [
    "CacheConfig",
    "EngineConfig",
    "ParallelConfig",
    "SamplingParams",
    "SchedulerConfig",
    "__version__",
]


def __getattr__(name):
    # Lazy import so `import gllm_tpu` works without pulling jax (fast CLI /
    # pure-control-plane uses).
    if name == "LLM":
        from gllm_tpu.engine.llm import LLM
        return LLM
    if name == "RequestOutput":
        from gllm_tpu.engine.llm import RequestOutput
        return RequestOutput
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
